"""dolo-lint suite tests: each checker catches its planted bug, clean code passes,
suppressions and the baseline round-trip, and the whole repo is clean (tier-1 gate).

Fixture files are written under a tmp directory laid out like the repo
(``dolomite_engine_tpu/models/...``) and passed explicitly, so the path-scoped rules
engage without touching the real tree.
"""

import json
import os
import subprocess
import sys
import time
from collections import Counter

import pytest

from tools.lint import all_checkers, all_rules, run_lint
from tools.lint.checkers.config_drift import ConfigDriftChecker
from tools.lint.checkers.kernels import KernelContractChecker
from tools.lint.checkers.sharding import ShardingChecker, parse_logical_axes, parse_mesh_axes
from tools.lint.checkers.telemetry import TelemetryChecker
from tools.lint.checkers.tracer import TracerChecker
from tools.lint.checkers.tracing import TracingChecker
from tools.lint.framework import (
    REPO_ROOT,
    Finding,
    SourceFile,
    load_baseline,
    run_checkers,
    save_baseline,
)

_SHARDING_PY = os.path.join(REPO_ROOT, "dolomite_engine_tpu", "parallel", "sharding.py")
_MESH_PY = os.path.join(REPO_ROOT, "dolomite_engine_tpu", "parallel", "mesh.py")


def _sharding_checker() -> ShardingChecker:
    return ShardingChecker(
        logical_axes=parse_logical_axes(open(_SHARDING_PY).read()),
        mesh_axes=parse_mesh_axes(open(_MESH_PY).read()),
    )


def _lint_snippet(tmp_path, rel, source, checkers):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(source)
    result = run_checkers(
        checkers, repo_root=str(tmp_path), files=[str(path)], baseline=Counter()
    )
    return result.new_findings


# ---------------------------------------------------------------- vocabularies


def test_vocabularies_parse_from_source_of_truth():
    logical = parse_logical_axes(open(_SHARDING_PY).read())
    mesh = parse_mesh_axes(open(_MESH_PY).read())
    assert {"vocab", "embed", "heads", "mlp", "experts", "act_batch", "act_seq"} <= logical
    assert mesh == {"dp", "fsdp", "sp", "tp", "ep"}
    assert not (logical & mesh)  # the two namespaces must never collide


# ---------------------------------------------------------------- sharding rules


def test_sharding_rule_fires_on_seed_defect_pattern(tmp_path):
    """The exact seed failure class: a logical-axis PartitionSpec leaking into a
    mesh-axis position. The rule must fire at the right file:line."""
    source = (
        "from jax.sharding import NamedSharding, PartitionSpec\n"
        "import flax.linen as nn\n"
        "\n"
        "def shard(mesh, x, init):\n"
        "    spec = PartitionSpec('vocab', 'embed')\n"  # line 5
        "    boxed = nn.with_partitioning(init, ('vocab', 'embed'))\n"  # line 6
        "    return NamedSharding(mesh, spec)\n"
    )
    findings = _lint_snippet(
        tmp_path, "dolomite_engine_tpu/models/bad.py", source, [_sharding_checker()]
    )
    leaks = [f for f in findings if f.rule == "sharding-logical-axis-in-mesh-spec"]
    assert {(f.path, f.line) for f in leaks} == {("dolomite_engine_tpu/models/bad.py", 5)}
    assert {f.message.split("'")[1] for f in leaks} == {"vocab", "embed"}
    boxes = [f for f in findings if f.rule == "sharding-raw-partitioning-box"]
    assert [(f.path, f.line) for f in boxes] == [("dolomite_engine_tpu/models/bad.py", 6)]


def test_sharding_rule_undeclared_mesh_axis(tmp_path):
    source = (
        "from jax.sharding import PartitionSpec\n"
        "spec = PartitionSpec('tp', 'model')\n"  # 'model' is not a declared axis
    )
    findings = _lint_snippet(
        tmp_path, "dolomite_engine_tpu/serving/bad.py", source, [_sharding_checker()]
    )
    assert [f.rule for f in findings] == ["sharding-undeclared-mesh-axis"]
    assert "'model'" in findings[0].message and findings[0].line == 2


def test_sharding_rule_flax_logical_constraint_and_typo(tmp_path):
    source = (
        "import flax.linen as nn\n"
        "from dolomite_engine_tpu.parallel.sharding import logical_constraint\n"
        "def f(x):\n"
        "    x = nn.with_logical_constraint(x, ('act_batch', None))\n"  # line 4: flax's
        "    return logical_constraint(x, ('act_batch', 'act_typo'))\n"  # line 5: typo
    )
    findings = _lint_snippet(
        tmp_path, "dolomite_engine_tpu/models/bad2.py", source, [_sharding_checker()]
    )
    rules = {f.rule: f.line for f in findings}
    assert rules["sharding-flax-logical-constraint"] == 4
    assert rules["sharding-unknown-logical-axis"] == 5


def test_sharding_clean_code_passes(tmp_path):
    source = (
        "from jax.sharding import NamedSharding, PartitionSpec\n"
        "import flax.linen as nn\n"
        "from dolomite_engine_tpu.parallel.sharding import logical_constraint\n"
        "def f(mesh, x, init):\n"
        "    boxed = nn.with_logical_partitioning(init, ('vocab', 'embed'))\n"
        "    x = logical_constraint(x, ('act_batch', 'act_seq', 'act_embed'))\n"
        "    return NamedSharding(mesh, PartitionSpec(('dp', 'fsdp'), 'tp'))\n"
    )
    findings = _lint_snippet(
        tmp_path, "dolomite_engine_tpu/models/good.py", source, [_sharding_checker()]
    )
    assert findings == []


# ---------------------------------------------------------------- tracer rules


def test_tracer_rules_fire_in_model_call(tmp_path):
    source = (
        "import numpy as np\n"
        "import flax.linen as nn\n"
        "class Block(nn.Module):\n"
        "    def __call__(self, x):\n"
        "        if bool(x.sum()):\n"  # line 5: python cast on traced value
        "            x = np.maximum(x, 0)\n"  # line 6: host numpy on traced value
        "        return x.mean().item()\n"  # line 7: device sync
        "    def helper(self, n):\n"
        "        return int(n)\n"  # host-side method: NOT flagged
    )
    findings = _lint_snippet(
        tmp_path, "dolomite_engine_tpu/models/bad3.py", source, [TracerChecker()]
    )
    got = {(f.rule, f.line) for f in findings}
    assert got == {
        ("tracer-python-cast", 5),
        ("tracer-numpy-call", 6),
        ("tracer-host-item", 7),
    }


def test_tracer_scopes_ops_by_annotation_and_serving_by_jit(tmp_path):
    ops_source = (
        "import jax\n"
        "import numpy as np\n"
        "def traced(x: jax.Array):\n"
        "    return np.abs(x)\n"  # line 4: flagged (jax.Array-annotated signature)
        "def host_preprocess(tokens):\n"
        "    return np.abs(tokens)\n"  # untraced host helper: NOT flagged
    )
    findings = _lint_snippet(
        tmp_path, "dolomite_engine_tpu/ops/bad4.py", ops_source, [TracerChecker()]
    )
    assert {(f.rule, f.line) for f in findings} == {("tracer-numpy-call", 4)}

    serving_source = (
        "import jax\n"
        "import numpy as np\n"
        "def _decode_impl(tokens):\n"
        "    return np.argmax(tokens)\n"  # line 4: flagged (jit'd below)
        "def host_loop(tokens):\n"
        "    return np.argmax(tokens)\n"  # NOT flagged\n"
        "step = jax.jit(_decode_impl)\n"
    )
    findings = _lint_snippet(
        tmp_path, "dolomite_engine_tpu/serving/bad5.py", serving_source, [TracerChecker()]
    )
    assert {(f.rule, f.line) for f in findings} == {("tracer-numpy-call", 4)}


# ---------------------------------------------------------------- telemetry rules


def test_telemetry_rules_fire_on_undeclared_names(tmp_path):
    source = (
        "from dolomite_engine_tpu.utils.telemetry import get_telemetry\n"
        "get_telemetry().count('made_up_counter')\n"  # line 2
        "get_telemetry().gauge('mystery/gauge', 1.0)\n"  # line 3
        "get_telemetry().emit_record('undeclared_kind', step=1)\n"  # line 4
    )
    checker = TelemetryChecker()
    findings = _lint_snippet(
        tmp_path, "dolomite_engine_tpu/serving/bad6.py", source, [checker]
    )
    undeclared = [f for f in findings if f.rule == "telemetry-undeclared-name"]
    assert [(f.line, f.message.split("'")[1]) for f in undeclared] == [
        (2, "made_up_counter"),
        (3, "mystery/gauge"),
        (4, "undeclared_kind"),
    ]
    # reverse direction fires too: a fixture tree uses none of the declared names
    dead = [f for f in findings if f.rule == "telemetry-dead-declaration"]
    assert dead, "declared-but-unused names must be reported"


def test_telemetry_dead_declaration_names_exact_set():
    """Planted registry: the dead-declaration rule reports exactly the declared names
    with no emit site — the guarantee the live /metrics endpoint leans on (every name
    it renders has a writer somewhere in the package)."""
    import ast

    from tools.lint.checkers.telemetry import reverse_errors, scan_tree

    tables = {
        "counters": {"live_counter", "dead_counter"},
        "events": set(),
        "gauges": {"live/gauge", "dead/gauge"},
        "records": {"live_kind": ("step",), "dead_kind": ("step",)},
    }
    source = (
        "get_telemetry().count('live_counter')\n"
        "get_telemetry().gauge('live/gauge', 1.0)\n"
        "get_telemetry().emit_record('live_kind', step=0)\n"
    )
    errors, usage = scan_tree(ast.parse(source), "fixture.py", tables)
    assert errors == []  # everything emitted is declared
    dead = sorted(message.split("'")[1] for message in reverse_errors(tables, usage))
    assert dead == ["dead/gauge", "dead_counter", "dead_kind"]


def test_telemetry_dead_declaration_clean_on_real_registry():
    """Every KNOWN_COUNTERS / KNOWN_GAUGES name (and record kind, incl. `fleet`) has at
    least one emit site in the real package — scrape parity starts here."""
    checker = TelemetryChecker()
    files = [
        os.path.join(root, name)
        for root, _, names in os.walk(os.path.join(REPO_ROOT, "dolomite_engine_tpu"))
        for name in names
        if name.endswith(".py")
    ]
    result = run_checkers([checker], repo_root=REPO_ROOT, files=files, baseline=Counter())
    dead = [f for f in result.new_findings if f.rule == "telemetry-dead-declaration"]
    assert dead == [], [f.message for f in dead]


def test_telemetry_shim_keeps_script_api(tmp_path):
    """scripts/check_telemetry_schema.py stays a working standalone entrypoint."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(REPO_ROOT, "scripts", "check_telemetry_schema.py"),
    )
    shim = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(shim)
    assert shim.check_package() == []
    bad = tmp_path / "bad.py"
    bad.write_text("get_telemetry().count('nope_counter')\n")
    errors = shim.check_package(str(tmp_path))
    assert any("nope_counter" in e and "bad.py:1" in e for e in errors)


# ---------------------------------------------------------------- tracing spans


def test_tracing_rule_fires_on_unknown_span(tmp_path):
    source = (
        "from dolomite_engine_tpu.utils.tracing import RequestTrace\n"
        "def f(state):\n"
        "    tr = state.trace\n"
        "    span = tr.begin('made_up_span')\n"  # line 4: not in KNOWN_SPANS
        "    ok = tr.begin('queue_wait')\n"  # declared: clean
        "    other = state.trace.begin('bogus_phase')\n"  # line 6: attribute receiver
        "    tr.end(span)\n"
    )
    findings = _lint_snippet(
        tmp_path, "dolomite_engine_tpu/serving/bad7.py", source, [TracingChecker()]
    )
    unknown = [f for f in findings if f.rule == "tracing-unknown-span"]
    assert [(f.line, f.message.split("'")[1]) for f in unknown] == [
        (4, "made_up_span"),
        (6, "bogus_phase"),
    ]
    # reverse direction: a fixture tree that begins almost nothing reports the
    # declared-but-unused names (the real repo covers all of them — see the
    # whole-repo-clean test)
    dead = {f.message.split("'")[1] for f in findings if f.rule == "tracing-dead-span"}
    assert "decode" in dead and "queue_wait" not in dead


def test_tracing_rule_ignores_unrelated_begin_calls(tmp_path):
    source = (
        "class Transaction:\n"
        "    def begin(self, name):\n"
        "        return name\n"
        "def f(db):\n"
        "    db.begin('made_up_span')\n"  # not a trace receiver: no finding
    )
    findings = _lint_snippet(
        tmp_path, "dolomite_engine_tpu/serving/bad8.py", source, [TracingChecker()]
    )
    assert [f for f in findings if f.rule == "tracing-unknown-span"] == []


# ---------------------------------------------------------------- kernel contract


def test_kernel_contract_detects_drift():
    checker = KernelContractChecker()
    checker._families = {"rmsnorm", "brand_new_kernel"}
    checker._config_fields = {"rmsnorm"}
    checker._args_fields = {"rmsnorm", "stale_family"}
    checker._gated = {"rmsnorm"}
    checker._parity_source = "kernel_overrides(rmsnorm='pallas')"
    messages = [f.message for f in checker.finalize()]
    assert any("'brand_new_kernel' is in KERNEL_FAMILIES but not a KernelConfig" in m for m in messages)
    assert any("'stale_family' names no kernel family" in m for m in messages)
    assert any("no KernelArgs field" in m and "brand_new_kernel" in m for m in messages)
    assert any("no use_pallas('brand_new_kernel')" in m for m in messages)
    assert any("never appears in the interpret-mode parity tests" in m for m in messages)


def test_kernel_contract_clean_on_repo():
    checker = KernelContractChecker()
    result = run_checkers([checker], baseline=Counter())
    assert result.new_findings == []
    assert checker._families == {
        "splash_attention",
        "paged_attention",
        "prefill_attention",
        "paged_kv_quant",
        "rmsnorm",
        "moe_dispatch",
        "fused_ce",
        "fused_rope_qkv",
    }


def test_kernel_unknown_family_flagged(tmp_path):
    source = (
        "from dolomite_engine_tpu.ops.pallas import use_pallas\n"
        "if use_pallas('nonexistent_kernel'):\n"
        "    pass\n"
    )
    checker = KernelContractChecker()
    checker.start(REPO_ROOT)
    path = tmp_path / "dolomite_engine_tpu" / "ops" / "bad7.py"
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(source)
    f = SourceFile.load(str(path), str(tmp_path))
    findings = checker.visit_file(f)
    assert [x.rule for x in findings] == ["kernel-unknown-family"]
    assert findings[0].line == 2


# ---------------------------------------------------------------- config drift


def test_config_unknown_field_flagged(tmp_path):
    import dolomite_engine_tpu.arguments as arguments_module

    checker = ConfigDriftChecker()
    findings = []
    checker._walk_yaml(
        arguments_module.TrainingArgs,
        {"model_args": {"model_class": "AutoModelForCausalLM", "bogus_knob": 1}, "typo_args": {}},
        ["model_args:", "  bogus_knob: 1", "typo_args:"],
        "configs/fake.yml",
        "",
        findings,
    )
    got = {f.message.split("'")[1] for f in findings}
    assert got == {"model_args.bogus_knob", "typo_args"}
    assert all(f.rule == "config-unknown-field" for f in findings)


def test_config_gradient_checkpointing_args_key_vocabulary():
    """A typo inside the plain-dict gradient_checkpointing_args block — key OR policy
    value — must fail lint, not a run (ISSUE 14 satellite)."""
    import dolomite_engine_tpu.arguments as arguments_module

    checker = ConfigDriftChecker()
    findings = []
    checker._walk_yaml(
        arguments_module.TrainingArgs,
        {
            "distributed_args": {
                "gradient_checkpointing_args": {
                    "checkpoint_every": 2,
                    "polcy": "save_dots",  # typo'd key
                    "policy": "save_dotz",  # typo'd value
                }
            }
        },
        ["distributed_args:", "  gradient_checkpointing_args:", "    polcy: save_dots"],
        "configs/fake.yml",
        "",
        findings,
    )
    assert len(findings) == 2
    assert all(f.rule == "config-unknown-field" for f in findings)
    messages = " | ".join(f.message for f in findings)
    assert "polcy" in messages and "save_dotz" in messages

    # the valid spellings pass clean
    findings = []
    checker._walk_yaml(
        arguments_module.TrainingArgs,
        {
            "distributed_args": {
                "gradient_checkpointing_args": {
                    "checkpoint_every": 2,
                    "policy": "save_attention_out",
                }
            }
        },
        ["distributed_args:"],
        "configs/fake.yml",
        "",
        findings,
    )
    assert findings == []


def test_config_policy_vocabulary_matches_models():
    """The lint table mirrors models/gpt_dolomite.REMAT_POLICY_NAMES — drift between
    the two would re-open the typo hole."""
    from dolomite_engine_tpu.models.gpt_dolomite import REMAT_POLICY_NAMES
    from tools.lint.checkers.config_drift import _DICT_FIELD_KEYS

    vocab = _DICT_FIELD_KEYS[("DistributedArgs", "gradient_checkpointing_args")]
    assert vocab["values"]["policy"] == set(REMAT_POLICY_NAMES)


def test_config_dead_field_detection(tmp_path):
    checker = ConfigDriftChecker()
    checker._repo_root = str(tmp_path)  # no configs/ -> YAML pass is a no-op
    checker._fields = [("FakeArgs", "used_field", 10), ("FakeArgs", "never_read", 11)]
    consumer = tmp_path / "dolomite_engine_tpu" / "consumer.py"
    consumer.parent.mkdir(parents=True, exist_ok=True)
    consumer.write_text("def f(args):\n    return args.used_field\n")
    checker.visit_file(SourceFile.load(str(consumer), str(tmp_path)))
    findings = checker.finalize()
    assert [f.rule for f in findings] == ["config-dead-field"]
    assert "FakeArgs.never_read" in findings[0].message and findings[0].line == 11


# ---------------------------------------------------------------- suppressions & baseline


def test_inline_suppression_round_trip(tmp_path):
    base = "from jax.sharding import PartitionSpec\n"
    line = "spec = PartitionSpec('vocab')"
    for suffix, expect in [
        ("", 1),
        ("  # dolint: disable=sharding-logical-axis-in-mesh-spec", 0),
        ("  # dolint: disable", 0),
        ("  # dolint: disable=some-other-rule", 1),
    ]:
        findings = _lint_snippet(
            tmp_path,
            f"dolomite_engine_tpu/s{expect}{len(suffix)}.py",
            base + line + suffix + "\n",
            [_sharding_checker()],
        )
        assert len(findings) == expect, suffix


def test_baseline_round_trip(tmp_path):
    baseline_path = tmp_path / "baseline.json"
    findings = [
        Finding("sharding-logical-axis-in-mesh-spec", "a.py", 5, "leak one"),
        Finding("sharding-logical-axis-in-mesh-spec", "a.py", 9, "leak one"),  # same key x2
        Finding("config-dead-field", "b.py", 1, "dead"),
    ]
    save_baseline(findings, str(baseline_path))
    loaded = load_baseline(str(baseline_path))
    assert loaded["sharding-logical-axis-in-mesh-spec::a.py::leak one"] == 2
    assert loaded["config-dead-field::b.py::dead"] == 1
    # a baselined finding is absorbed; an extra occurrence beyond the count is NEW
    data = json.loads(baseline_path.read_text())
    assert set(data) == {"_comment", "findings"}


def test_baseline_absorbs_exact_counts(tmp_path):
    source = (
        "from jax.sharding import PartitionSpec\n"
        "a = PartitionSpec('vocab')\n"
        "b = PartitionSpec('vocab')\n"
    )
    path = tmp_path / "dolomite_engine_tpu" / "models" / "two.py"
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(source)
    result = run_checkers(
        [_sharding_checker()], repo_root=str(tmp_path), files=[str(path)], baseline=Counter()
    )
    assert len(result.new_findings) == 2
    baseline = Counter({result.new_findings[0].baseline_key(): 1})
    result = run_checkers(
        [_sharding_checker()], repo_root=str(tmp_path), files=[str(path)], baseline=baseline
    )
    assert len(result.new_findings) == 1  # one absorbed, the second occurrence still reported
    baseline = Counter({result.findings[0].baseline_key(): 2})
    result = run_checkers(
        [_sharding_checker()], repo_root=str(tmp_path), files=[str(path)], baseline=baseline
    )
    assert result.new_findings == [] and result.stale_baseline == []


# ---------------------------------------------------------------- whole repo (tier-1 gate)


def test_whole_repo_is_clean_and_fast():
    """Acceptance: the full suite over the real repo has zero non-baselined findings and
    stays fast enough to gate (CI budget: 30s; typical: ~2s)."""
    t0 = time.monotonic()
    result = run_lint()
    elapsed = time.monotonic() - t0
    assert result.new_findings == [], "\n".join(f.render() for f in result.new_findings)
    assert result.files_scanned > 100
    assert elapsed < 30, f"dolo-lint took {elapsed:.1f}s; must stay fast enough to gate tier-1"


def test_rule_ids_unique_and_documented():
    rules = all_rules()
    assert len(rules) == len(set(rules))
    doc = open(os.path.join(REPO_ROOT, "docs", "STATIC_ANALYSIS.md")).read()
    for rule in rules:
        assert f"`{rule}`" in doc, f"rule {rule} missing from docs/STATIC_ANALYSIS.md"


def test_cli_smoke():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--list-rules"],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0
    assert "sharding-logical-axis-in-mesh-spec" in proc.stdout
    # (the full `python -m tools.lint` gate is exercised in-process by
    # test_whole_repo_is_clean_and_fast; no second interpreter spin-up here)
