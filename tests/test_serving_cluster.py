"""Distributed serving tier tests (serving/cluster/): TP-sharded replica parity,
prefill/decode disaggregation with KV handoff, and the telemetry-driven router.

Parity bars match the single-engine suites: TP=2 decode is asserted TOKEN-FOR-TOKEN
(greedy bit-exact, sampled too) against the TP=1 engine with paged pool + prefix cache +
chunked prefill all active, and the disaggregated prefill->decode path against the
monolithic engine — both with `decode_compiles == 1`.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dolomite_engine_tpu.models.gpt_dolomite import GPTDolomiteForCausalLM
from dolomite_engine_tpu.serving import (
    DisaggregatedEngine,
    EngineReplica,
    KVHandoff,
    QueueFullError,
    RequestStatus,
    Router,
    SamplingParams,
    ServingEngine,
    inference_mesh,
    make_sharded_engine,
    route_batch,
    serve_batch,
)

from .test_commons import get_dense_test_config

PAGE = 16


def _tiny_model():
    config = get_dense_test_config("gqa", "rope", normalization_function="rmsnorm")
    model = GPTDolomiteForCausalLM(config=config)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return config, model, params


def _random_prompt(rs, config, length):
    return list(map(int, rs.randint(3, config.vocab_size, length)))


def _engine_kwargs(config, **overrides):
    kwargs = dict(
        num_slots=2,
        max_len=96,
        prefill_bucket_multiple=8,
        eos_token_id=None,
        pad_token_id=config.pad_token_id,
        page_size=PAGE,
        prefill_chunk_tokens=16,  # long prompts need >= 2 chunks: chunked path active
    )
    kwargs.update(overrides)
    return kwargs


def _mixed_workload(config, rs):
    """Shared page-aligned prefix + unique tails (prefix cache engages), mixed greedy
    and sampled rows, per-request rngs — the full paged+prefix+chunked regime."""
    shared = _random_prompt(rs, config, 2 * PAGE)
    prompts = [
        shared + _random_prompt(rs, config, 5),
        _random_prompt(rs, config, 41),
        shared + _random_prompt(rs, config, 9),
        _random_prompt(rs, config, 7),
    ]
    samplings = [
        SamplingParams(),  # greedy: the bit-exact acceptance row
        SamplingParams(do_sample=True, temperature=0.8),
        SamplingParams(do_sample=True, temperature=1.2, top_k=7),
        SamplingParams(do_sample=True, top_p=0.9),
    ]
    rngs = [jax.random.PRNGKey(100 + i) for i in range(len(prompts))]
    return [
        dict(prompt_ids=prompts[i], max_new_tokens=6, sampling=samplings[i], rng=rngs[i])
        for i in range(len(prompts))
    ]


# ------------------------------------------------------------------- sharded replicas


def test_tp2_engine_parity_token_for_token(eight_devices):
    """TP=2 sharded engine (2-device mesh, params + KV heads sharded) decodes every
    request token-for-token like the TP=1 engine — greedy asserted bit-exact, sampled
    rows too — with paged pool, prefix hits, and chunked prefill active, and exactly
    one compiled decode step."""
    config, model, params = _tiny_model()
    rs = np.random.RandomState(0)
    specs = _mixed_workload(config, rs)

    baseline = ServingEngine(model, params, **_engine_kwargs(config))
    expected = [s.tokens for s in serve_batch(baseline, [dict(s) for s in specs])]

    mesh = inference_mesh(tensor_parallel_size=2, devices=eight_devices[:2])
    sharded = make_sharded_engine(model, params, mesh=mesh, **_engine_kwargs(config))
    got = [s.tokens for s in serve_batch(sharded, [dict(s) for s in specs])]

    assert got[0] == expected[0]  # greedy row: bit-exact across topologies
    assert got == expected  # sampled rows follow the same rng stream
    assert sharded.decode_compiles == 1  # sharding must not break compile-once
    assert sharded.stats.prefix_hit_tokens > 0  # the shared prefix actually engaged

    # the paged pool really is sharded: kv heads (dim 2) split over tp
    spec = sharded.pool.caches[0]["k"].sharding.spec
    assert tuple(spec) == (None, None, "tp")


def test_sharded_pool_head_fallback(eight_devices):
    """kv heads that don't divide tp fall back to replication instead of erroring
    (the prune_indivisible escape hatch, serving-side)."""
    from dolomite_engine_tpu.serving import PagedKVCachePool

    config, model, _ = _tiny_model()  # gqa: 2 kv heads
    mesh = inference_mesh(tensor_parallel_size=8, devices=eight_devices)
    pool = PagedKVCachePool(model, 2, 64, PAGE, mesh=mesh)
    assert tuple(pool.caches[0]["k"].sharding.spec) == ()  # 2 % 8 != 0 -> replicated


def test_inference_mesh_validation(eight_devices):
    with pytest.raises(ValueError):
        inference_mesh(tensor_parallel_size=3, devices=eight_devices[:2])
    mesh = inference_mesh(tensor_parallel_size=2, expert_parallel_size=2, devices=eight_devices[:4])
    assert dict(zip(mesh.axis_names, mesh.devices.shape))["tp"] == 2
    assert dict(zip(mesh.axis_names, mesh.devices.shape))["ep"] == 2


def test_engine_mesh_requires_rules():
    config, model, params = _tiny_model()
    with pytest.raises(ValueError):
        ServingEngine(model, params, num_slots=1, max_len=32, mesh=object())


# --------------------------------------------------------------------- disaggregation


def _build_disagg(config, model, params, num_workers=2, clock=None, **prefill_overrides):
    extra = {} if clock is None else {"clock": clock}
    prefill = ServingEngine(
        model, params, **_engine_kwargs(config, prefill_only=True, **extra, **prefill_overrides)
    )
    workers = [
        ServingEngine(model, params, **_engine_kwargs(config, **extra))
        for _ in range(num_workers)
    ]
    return DisaggregatedEngine(prefill, workers)


def test_disaggregated_parity_token_for_token():
    """Prefill worker -> KV handoff -> decode worker reproduces the monolithic engine
    token-for-token on the same requests (greedy and sampled), and the handoff seam
    actually transferred pages."""
    config, model, params = _tiny_model()
    rs = np.random.RandomState(1)
    specs = _mixed_workload(config, rs)

    mono = ServingEngine(model, params, **_engine_kwargs(config))
    expected = [s.tokens for s in serve_batch(mono, [dict(s) for s in specs])]

    disagg = _build_disagg(config, model, params)
    states = [disagg.submit(**dict(s)) for s in specs]
    disagg.drain()
    assert [s.tokens for s in states] == expected
    assert all(s.status == RequestStatus.completed for s in states)
    assert disagg.handoff.transfers == len(specs)
    assert disagg.handoff.mean_latency_s > 0
    for worker in disagg.workers:
        assert worker.decode_compiles <= 1  # one idle worker may never compile
    # prefill worker never decodes; decode workers never prefill
    assert disagg.prefill.stats.decode_tokens == 0
    assert all(w.stats.prefill_tokens == 0 for w in disagg.workers)
    # every slot on both sides came back
    assert disagg.prefill.pool.num_free == disagg.prefill.pool.num_slots
    assert all(w.pool.num_free == w.pool.num_slots for w in disagg.workers)


def test_kv_handoff_copies_page_bytes():
    """The transferred pages hold byte-identical K/V in the destination pool."""
    config, model, params = _tiny_model()
    disagg = _build_disagg(config, model, params, num_workers=1)
    rs = np.random.RandomState(2)
    prompt = _random_prompt(rs, config, 2 * PAGE + 3)  # 3 pages: 2 full + tail

    captured = {}
    original_transfer = disagg.handoff.transfer

    def capture(src_pool, src_pages, dst_pool, dst_pages):
        captured["src"] = [np.asarray(src_pool.caches[0]["k"][p]) for p in src_pages]
        captured["pages"] = (list(src_pages), list(dst_pages))
        original_transfer(src_pool, src_pages, dst_pool, dst_pages)
        captured["dst"] = [np.asarray(dst_pool.caches[0]["k"][p]) for p in dst_pages]

    disagg.handoff.transfer = capture
    state = disagg.submit(prompt_ids=prompt, max_new_tokens=4, rng=jax.random.PRNGKey(5))
    disagg.drain()
    assert state.status == RequestStatus.completed
    src_pages, dst_pages = captured["pages"]
    assert len(src_pages) == 3  # ceil(35 / 16)
    for src, dst in zip(captured["src"], captured["dst"]):
        np.testing.assert_array_equal(src, dst)


def test_handoff_page_size_mismatch_rejected():
    config, model, params = _tiny_model()
    prefill = ServingEngine(model, params, **_engine_kwargs(config, prefill_only=True))
    worker = ServingEngine(model, params, **_engine_kwargs(config, page_size=8))
    with pytest.raises(ValueError):
        DisaggregatedEngine(prefill, [worker])


def test_prefill_only_contract():
    config, model, params = _tiny_model()
    with pytest.raises(ValueError):  # disaggregation is a paged-pool feature
        ServingEngine(model, params, num_slots=1, max_len=32, paged=False, prefill_only=True)
    with pytest.raises(ValueError):  # prefill workers never decode, so never speculate
        ServingEngine(
            model, params, num_slots=1, max_len=32, prefill_only=True, speculate_ngram=True
        )

    engine = ServingEngine(model, params, **_engine_kwargs(config, prefill_only=True))
    rs = np.random.RandomState(3)
    streamed = []
    state = engine.submit(
        prompt_ids=_random_prompt(rs, config, 20),
        max_new_tokens=4,
        on_token=streamed.append,
    )
    for _ in range(8):
        engine.step()
    # prefill finished: first token streamed, request parked (not decoded, not done)
    assert state.tokens == streamed and len(streamed) == 1
    assert engine.pending_handoffs == 1
    assert not engine.has_work()  # parked work is the adopter's, not the stepper's
    assert engine.stats.decode_tokens == 0


def test_disagg_deadline_cancellation_spans_handoff():
    """A deadline keeps binding after the request crosses the prefill->decode boundary:
    both sides share the clock and the original submit time."""
    config, model, params = _tiny_model()
    now = [0.0]
    disagg = _build_disagg(config, model, params, num_workers=1, clock=lambda: now[0])
    rs = np.random.RandomState(4)
    state = disagg.submit(
        prompt_ids=_random_prompt(rs, config, 8), max_new_tokens=50, deadline_s=5.0
    )
    disagg.step()  # prefill + handoff + first decode steps
    disagg.step()
    assert state.status == RequestStatus.running and state.slot is not None
    now[0] = 10.0  # deadline passes mid-decode, on the DECODE worker
    disagg.drain()
    assert state.status == RequestStatus.cancelled
    assert disagg.workers[0].pool.num_free == disagg.workers[0].pool.num_slots


# ---------------------------------------------------------------------------- router


def test_router_least_loaded_and_rejection():
    config, model, params = _tiny_model()
    engines = [
        ServingEngine(model, params, **_engine_kwargs(config, max_waiting=2))
        for _ in range(2)
    ]
    router = Router([EngineReplica(i, e) for i, e in enumerate(engines)])
    rs = np.random.RandomState(5)
    # unique prompts (no affinity): submissions alternate by queue depth
    for _ in range(4):
        router.submit(prompt_ids=_random_prompt(rs, config, 9), max_new_tokens=2)
    assert router.stats.per_replica_routed == {0: 2, 1: 2}
    # both queues full (bound 2 each, nothing stepped): the fleet rejects
    with pytest.raises(QueueFullError):
        for _ in range(8):
            router.submit(prompt_ids=_random_prompt(rs, config, 9), max_new_tokens=2)
    assert router.stats.rejected == 1
    router.drain()
    assert sum(e.stats.completed for e in engines) == router.stats.routed


def test_router_fcfs_and_deadline_through_router():
    """Per replica, requests finish in submission order (FCFS is preserved through the
    routing layer) and a lapsed deadline still cancels — waiting or mid-decode."""
    config, model, params = _tiny_model()
    now = [0.0]
    engines = [
        ServingEngine(
            model, params, **_engine_kwargs(config, num_slots=1, clock=lambda: now[0])
        )
        for _ in range(2)
    ]
    replicas = [EngineReplica(i, e) for i, e in enumerate(engines)]
    router = Router(replicas)
    rs = np.random.RandomState(6)
    finish_order: list[int] = []
    states, homes = [], []
    for i in range(6):
        state = router.submit(
            prompt_ids=_random_prompt(rs, config, 9),
            max_new_tokens=3,
            on_finish=lambda st, i=i: finish_order.append(i),
        )
        states.append(state)
        homes.append(
            next(r.replica_id for r in replicas if state in r.engine.scheduler.waiting)
        )
    doomed = router.submit(
        prompt_ids=_random_prompt(rs, config, 9), max_new_tokens=3, deadline_s=1.0
    )
    now[0] = 5.0  # the deadline lapses while it waits behind a full replica
    router.drain()
    assert all(s.status == RequestStatus.completed for s in states)
    assert doomed.status == RequestStatus.cancelled
    for replica_id in (0, 1):
        mine = [i for i in range(6) if homes[i] == replica_id]
        finished_mine = [i for i in finish_order if i in mine]
        assert finished_mine == mine, f"replica {replica_id} broke FCFS"


def test_router_prefix_affinity_and_replica_records(tmp_path):
    """The e2e acceptance: all admitted requests complete over 2 replicas; serving
    records carry each engine's replica_id; a repeated prompt routes to the replica
    whose prefix cache holds its pages; the router record lands in the sink."""
    from dolomite_engine_tpu.utils.telemetry import (
        Telemetry,
        install_telemetry,
        uninstall_telemetry,
    )

    config, model, params = _tiny_model()
    sink = tmp_path / "router.jsonl"
    telemetry = Telemetry(sink_path=str(sink), rank=0)
    install_telemetry(telemetry)
    try:
        engines = [ServingEngine(model, params, **_engine_kwargs(config)) for _ in range(2)]
        router = Router([EngineReplica(i, e) for i, e in enumerate(engines)])
        rs = np.random.RandomState(7)
        long_prompt = _random_prompt(rs, config, 2 * PAGE + 4)  # 2 full pages resident after
        states = route_batch(
            router,
            [dict(prompt_ids=long_prompt, max_new_tokens=4, rng=jax.random.PRNGKey(9))]
            + [
                dict(prompt_ids=_random_prompt(rs, config, 9), max_new_tokens=4)
                for _ in range(3)
            ],
        )
        assert all(s.status == RequestStatus.completed for s in states)
        home = next(i for i, e in enumerate(engines) if e.prefix_match_len(long_prompt) > 0)

        # the repeat must land on the page-holding replica via affinity, and hit
        again = router.submit(
            prompt_ids=long_prompt, max_new_tokens=4, rng=jax.random.PRNGKey(9)
        )
        router.drain()
        assert router.stats.affinity_hits == 1
        assert again.tokens == states[0].tokens  # prefix reuse is still token-exact
        assert engines[home].stats.prefix_hit_tokens > 0
        assert engines[1 - home].stats.prefix_hit_tokens == 0
    finally:
        uninstall_telemetry()
        telemetry.close()

    records = [json.loads(line) for line in open(sink)]
    servings = [r for r in records if r.get("kind") == "serving"]
    assert {r["replica_id"] for r in servings} == {0, 1}
    routers = [r for r in records if r.get("kind") == "router"]
    assert routers, "router.drain must emit a router record"
    last = routers[-1]
    assert last["replicas"] == 2 and last["routed"] == 5
    assert last["prefix_affinity_hits"] == 1
    assert len(last["queue_depths"]) == 2


def test_router_over_disaggregated_replicas():
    """The router composes with disaggregation: replicas that are prefill+decode pairs,
    with the handoff latency surfacing in the router record counters."""
    config, model, params = _tiny_model()
    replicas = [
        EngineReplica(i, _build_disagg(config, model, params, num_workers=1))
        for i in range(2)
    ]
    router = Router(replicas)
    rs = np.random.RandomState(8)
    states = route_batch(
        router,
        [
            dict(prompt_ids=_random_prompt(rs, config, 9 + 4 * i), max_new_tokens=3)
            for i in range(4)
        ],
    )
    assert all(s.status == RequestStatus.completed for s in states)
    assert sum(r.engine.handoff.transfers for r in replicas) == 4
    # replica ids were stamped on the underlying engines (prefill + workers)
    assert replicas[0].engine.prefill.replica_id == 0
    assert replicas[1].engine.workers[0].replica_id == 1


def test_router_threaded_mode_drains():
    """Threaded proof-of-concept: replicas step on background threads; the router only
    submits and waits. Every request completes and the engines stay consistent."""
    config, model, params = _tiny_model()
    engines = [ServingEngine(model, params, **_engine_kwargs(config)) for _ in range(2)]
    router = Router([EngineReplica(i, e) for i, e in enumerate(engines)])
    rs = np.random.RandomState(9)
    specs = [
        dict(prompt_ids=_random_prompt(rs, config, 9 + 2 * i), max_new_tokens=3)
        for i in range(4)
    ]
    router.start()
    try:
        states = [router.submit(**s) for s in specs]
        assert router.wait(timeout_s=120.0), "threaded fleet failed to drain"
    finally:
        router.stop()
    assert all(s.status == RequestStatus.completed for s in states)
    assert sum(e.stats.completed for e in engines) == 4
    assert all(e.pool.num_free == e.pool.num_slots for e in engines)


# ------------------------------------------------------------------------- generate.py


def test_generate_cli_distributed_path(tmp_path, monkeypatch, eight_devices):
    """generate.main with tensor_parallel_size=2 + replicas=2 + disaggregate: the full
    distributed stack behind the dataset-generation entry point still writes the jsonl
    in dataset order."""
    from dolomite_engine_tpu import generate as generate_module
    from dolomite_engine_tpu.arguments import InferenceArgs
    from dolomite_engine_tpu.model_wrapper import base as mw_base
    from dolomite_engine_tpu.parallel.mesh import MeshManager

    class _StubTokenizer:
        eos_token_id = 1
        pad_token_id = 2
        vocab_size = 2048

        def __len__(self):
            return self.vocab_size

        def decode(self, ids, skip_special_tokens=True):
            return " ".join(str(int(i)) for i in ids)

    monkeypatch.setattr(
        mw_base.ModelWrapper,
        "_setup_tokenizer",
        lambda self, name, extra: setattr(self, "tokenizer", _StubTokenizer()),
    )
    config = get_dense_test_config("mqa", "rope")
    args = InferenceArgs(
        model_args=dict(model_class="AutoModelForCausalLM", pretrained_config=config.to_dict()),
        datasets=[
            dict(
                class_name="DebugDataset",
                data_name="debug",
                class_args=dict(num_examples=5, token_id=5),
                max_input_tokens=6,
                max_output_tokens=4,
            )
        ],
        generation_parameters=dict(
            batch_size=2,
            max_new_tokens=3,
            tensor_parallel_size=2,
            replicas=2,
            disaggregate=True,
        ),
        output_dir=str(tmp_path / "out"),
    )
    MeshManager.destroy()
    try:
        generate_module.main(args=args)
    finally:
        MeshManager.destroy()

    lines = [json.loads(line) for line in open(tmp_path / "out" / "output-debug.jsonl")]
    assert len(lines) == 5
    assert all(0 <= line["num_generated_tokens"] <= 3 for line in lines)


# ------------------------------------------------------------------------- arguments


def test_generation_parameters_cluster_validation(eight_devices):
    from dolomite_engine_tpu.arguments import GenerationParameters

    base = dict(batch_size=2, max_new_tokens=4)
    assert GenerationParameters(**base).replicas == 1
    params = GenerationParameters(**base, tensor_parallel_size=2, replicas=3, disaggregate=True)
    assert (params.tensor_parallel_size, params.replicas, params.disaggregate) == (2, 3, True)
    with pytest.raises(ValueError):
        GenerationParameters(**base, replicas=0)
    with pytest.raises(ValueError):
        GenerationParameters(**base, tensor_parallel_size=0)
    with pytest.raises(ValueError):  # 8 virtual devices: 3 does not divide 8
        GenerationParameters(**base, tensor_parallel_size=3)
