"""Seq2seq configs must fail loudly: the registry is decoder-only (VERDICT r2 item 6).

Parity: reference `model_wrapper/base.py:42-83` actually finetunes AutoModelForSeq2SeqLM;
dolomite_engine_tpu does not, and must never silently train a causal LM instead.
"""

import pytest

from dolomite_engine_tpu.enums import Mode
from dolomite_engine_tpu.model_wrapper.base import ModelWrapper


def test_seq2seq_model_class_raises():
    with pytest.raises(NotImplementedError, match="Seq2Seq"):
        ModelWrapper(
            mode=Mode.training,
            pretrained_config={"model_type": "gpt_dolomite", "n_layer": 1, "n_embd": 32,
                               "n_head": 2, "vocab_size": 64, "n_positions": 32},
            model_class="AutoModelForSeq2SeqLM",
        )
