"""Shared test fixtures.

Parity: reference `tests/hf_models/test_common.py` (`TestCommons.get_dense_test_config`,
`get_moe_test_config`, `get_dummy_inputs`, `assert_equal_tensors`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from dolomite_engine_tpu.models.config import CommonConfig, MoEConfig

SEED = 42


def get_dense_test_config(
    attention_head_type: str = "mqa",
    position_embedding_type: str = "learned_absolute",
    num_layers: int = 4,
    add_bias: bool = True,
    activation_function: str = "gelu_pytorch_tanh",
    normalization_function: str = "layernorm",
    **kwargs,
) -> CommonConfig:
    num_kv = {"mha": None, "mqa": None, "gqa": 2}[attention_head_type]
    return CommonConfig(
        vocab_size=2048,
        n_positions=512,
        n_embd=32,
        n_layer=num_layers,
        n_head=4,
        num_key_value_heads=num_kv,
        attention_head_type=attention_head_type,
        position_embedding_type=position_embedding_type,
        add_bias=add_bias,
        activation_function=activation_function,
        normalization_function=normalization_function,
        resid_pdrop=0.0,
        embd_pdrop=0.0,
        attn_pdrop=0.0,
        bos_token_id=0,
        eos_token_id=1,
        pad_token_id=2,
        **kwargs,
    )


def get_moe_test_config(
    attention_head_type: str = "mqa",
    position_embedding_type: str = "learned_absolute",
    num_experts: int = 4,
    num_experts_per_tok: int = 2,
    **kwargs,
) -> MoEConfig:
    num_kv = {"mha": None, "mqa": None, "gqa": 2}[attention_head_type]
    return MoEConfig(
        vocab_size=2048,
        n_positions=512,
        n_embd=32,
        n_layer=4,
        n_head=4,
        num_key_value_heads=num_kv,
        attention_head_type=attention_head_type,
        position_embedding_type=position_embedding_type,
        num_experts=num_experts,
        num_experts_per_tok=num_experts_per_tok,
        resid_pdrop=0.0,
        embd_pdrop=0.0,
        attn_pdrop=0.0,
        bos_token_id=0,
        eos_token_id=1,
        pad_token_id=2,
        **kwargs,
    )


def get_dummy_inputs(config, batch: int = 2, seq: int = 16, padded: bool = True):
    rs = np.random.RandomState(SEED)
    input_ids = rs.randint(0, config.vocab_size, (batch, seq)).astype(np.int32)
    attention_mask = None
    if padded:
        attention_mask = np.ones((batch, seq), np.int32)
        attention_mask[0, : seq // 4] = 0  # left padding on row 0
    return jnp.asarray(input_ids), None if attention_mask is None else jnp.asarray(attention_mask)


def assert_allclose(a, b, atol=1e-5, rtol=1e-5, msg=""):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=atol, rtol=rtol, err_msg=msg)

