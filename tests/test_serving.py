"""Serving-engine tests: slot pool invariants, scheduler admission/deadlines, per-slot
sampling isolation, EOS termination, and end-to-end parity vs `generate_tokens`.

All model paths are unsharded (no mesh, no `init_params`) — the sharded-model path fails
at seed from the logical-axis rules skew and would mask the feature under test.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dolomite_engine_tpu.generation_utils import generate_tokens
from dolomite_engine_tpu.models.gpt_dolomite import GPTDolomiteForCausalLM
from dolomite_engine_tpu.ops.sampling import sample_token, sample_tokens_vectorized
from dolomite_engine_tpu.serving import (
    QueueFullError,
    Request,
    RequestStatus,
    SamplingParams,
    Scheduler,
    ServingEngine,
    SlotKVCachePool,
    serve_batch,
)

from .test_commons import get_dense_test_config


def _tiny_model():
    config = get_dense_test_config("gqa", "rope", normalization_function="rmsnorm")
    model = GPTDolomiteForCausalLM(config=config)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return config, model, params


def _random_prompt(rs, config, length):
    return list(map(int, rs.randint(3, config.vocab_size, length)))


# ---------------------------------------------------------------------------- pool


def test_pool_alloc_reclaim_invariants():
    config, model, _ = _tiny_model()
    pool = SlotKVCachePool(model, num_slots=3, max_len=16)

    slots = [pool.allocate() for _ in range(3)]
    assert slots == [0, 1, 2]  # lowest-first, deterministic
    assert pool.allocate() is None  # exhausted pool signals, never grows
    assert pool.num_free == 0 and pool.num_active == 3 and pool.occupancy == 1.0

    pool.lengths[1] = 7
    pool.free(1)
    assert pool.num_free == 1
    assert pool.lengths[1] == 0  # reclamation resets the validity frontier
    with pytest.raises(ValueError):
        pool.free(1)  # double free
    assert pool.allocate() == 1  # reclaimed slot is reusable

    # cache shapes are the static decode layout
    assert pool.caches[0]["k"].shape == (3, 16, config.num_key_value_heads, config.head_dim)
    assert len(pool.caches) == config.n_layer


def test_pool_write_prefill_requires_allocation():
    _, model, _ = _tiny_model()
    pool = SlotKVCachePool(model, num_slots=2, max_len=16)
    prefill = model.init_kv_caches(1, 8)
    with pytest.raises(ValueError):
        pool.write_prefill(0, prefill, 5)  # slot 0 was never allocated
    slot = pool.allocate()
    pool.write_prefill(slot, prefill, 5)
    assert pool.lengths[slot] == 5


# ---------------------------------------------------------------------------- scheduler


def test_scheduler_fcfs_and_queue_bound():
    scheduler = Scheduler(max_waiting=2)
    a = scheduler.submit(Request(prompt_ids=[1], max_new_tokens=1))
    b = scheduler.submit(Request(prompt_ids=[2], max_new_tokens=1))
    assert (a.request.request_id, b.request.request_id) == (0, 1)
    with pytest.raises(QueueFullError):
        scheduler.submit(Request(prompt_ids=[3], max_new_tokens=1))

    admit, dead = scheduler.admissible(free_slots=1)
    assert [s.request.request_id for s in admit] == [0] and not dead  # FCFS
    admit, _ = scheduler.admissible(free_slots=4)
    assert [s.request.request_id for s in admit] == [1]
    assert scheduler.queue_depth == 0


def test_scheduler_expired_waiters_are_not_admitted():
    now = [0.0]
    scheduler = Scheduler(max_waiting=4, clock=lambda: now[0])
    stale = scheduler.submit(Request(prompt_ids=[1], max_new_tokens=1, deadline_s=5.0))
    fresh = scheduler.submit(Request(prompt_ids=[2], max_new_tokens=1, deadline_s=None))
    now[0] = 10.0
    admit, dead = scheduler.admissible(free_slots=2)
    assert dead == [stale] and admit == [fresh]  # stale head never blocks the queue


# ---------------------------------------------------------------------------- sampling


def test_per_slot_sampling_param_isolation():
    """Every row of the vectorized sampler must reproduce a single-request sample_token
    call with that row's own params — no cross-slot leakage of temperature/top-k/top-p."""
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(6, 64).astype(np.float32) * 3)
    row_params = [
        dict(do_sample=False, temperature=None, top_k=None, top_p=None),
        dict(do_sample=True, temperature=None, top_k=None, top_p=None),
        dict(do_sample=True, temperature=0.7, top_k=None, top_p=None),
        dict(do_sample=True, temperature=1.3, top_k=5, top_p=None),
        dict(do_sample=True, temperature=None, top_k=None, top_p=0.8),
        dict(do_sample=True, temperature=0.9, top_k=10, top_p=0.95),
    ]
    keys = jnp.stack([jax.random.PRNGKey(100 + i) for i in range(len(row_params))])

    expected = [
        int(sample_token(logits[i : i + 1], keys[i], **p)[0]) for i, p in enumerate(row_params)
    ]
    encoded = [
        SamplingParams(**p).encoded() for p in row_params
    ]  # (do_sample, temperature, top_k, top_p)
    got = sample_tokens_vectorized(
        logits,
        keys,
        jnp.asarray([e[0] for e in encoded]),
        jnp.asarray([e[1] for e in encoded], jnp.float32),
        jnp.asarray([e[2] for e in encoded], jnp.int32),
        jnp.asarray([e[3] for e in encoded], jnp.float32),
    )
    assert expected == [int(t) for t in got]


# ---------------------------------------------------------------------------- engine


def test_engine_matches_generate_tokens_e2e():
    """Acceptance: requests with different prompt lengths and sampling params, submitted
    asynchronously, decode token-for-token like equivalent one-shot generate_tokens
    calls; the decode step compiles exactly once; every slot is reclaimed at drain."""
    config, model, params = _tiny_model()
    rs = np.random.RandomState(0)
    prompts = [_random_prompt(rs, config, n) for n in (7, 13, 4, 9, 17)]
    samplings = [
        SamplingParams(),
        SamplingParams(do_sample=True, temperature=0.8),
        SamplingParams(do_sample=True, temperature=1.2, top_k=7),
        SamplingParams(do_sample=True, top_p=0.9),
        SamplingParams(do_sample=True, temperature=0.7, top_k=20, top_p=0.95),
    ]
    rngs = [jax.random.PRNGKey(100 + i) for i in range(5)]
    max_new = 6

    engine = ServingEngine(
        model,
        params,
        num_slots=2,
        max_len=64,
        prefill_bucket_multiple=8,
        eos_token_id=None,
        pad_token_id=config.pad_token_id,
    )
    streamed: dict[int, list[int]] = {}

    def spec(i):
        return dict(
            prompt_ids=prompts[i],
            max_new_tokens=max_new,
            sampling=samplings[i],
            rng=rngs[i],
            on_token=lambda tok, i=i: streamed.setdefault(i, []).append(tok),
        )

    # asynchronous arrival: three requests up front, two more while decoding
    states = [engine.submit(**spec(i)) for i in range(3)]
    for _ in range(3):
        engine.step()
    states += [engine.submit(**spec(i)) for i in (3, 4)]
    engine.drain()

    for i, state in enumerate(states):
        ids = jnp.asarray([prompts[i]], jnp.int32)
        expected, num = generate_tokens(
            model,
            params,
            ids,
            jnp.ones_like(ids),
            rngs[i],
            max_new_tokens=max_new,
            do_sample=samplings[i].do_sample,
            temperature=samplings[i].temperature,
            top_k=samplings[i].top_k,
            top_p=samplings[i].top_p,
            eos_token_id=None,
            pad_token_id=config.pad_token_id,
        )
        assert state.status == RequestStatus.completed
        assert state.tokens == [int(t) for t in np.asarray(expected[0])]
        assert streamed[i] == state.tokens  # callbacks saw exactly the final tokens
        assert state.ttft_s is not None and state.ttft_s >= 0

    assert engine.decode_compiles == 1  # the static-shape invariant
    assert engine.pool.num_free == engine.pool.num_slots  # all slots reclaimed
    assert not engine.has_work()
    assert engine.stats.completed == 5 and engine.stats.cancelled == 0


def test_engine_eos_stops_and_frees_slot():
    config, model, params = _tiny_model()
    rs = np.random.RandomState(3)
    prompt = _random_prompt(rs, config, 6)
    max_new = 5

    # unconstrained run picks the fake EOS (2nd generated token), like test_generation
    engine = ServingEngine(
        model, params, num_slots=1, max_len=32, prefill_bucket_multiple=8,
        eos_token_id=None, pad_token_id=0,
    )
    free_run = serve_batch(
        engine, [dict(prompt_ids=prompt, max_new_tokens=max_new, rng=jax.random.PRNGKey(1))]
    )[0]
    fake_eos = free_run.tokens[1]
    first = free_run.tokens.index(fake_eos)

    engine2 = ServingEngine(
        model, params, num_slots=1, max_len=32, prefill_bucket_multiple=8,
        eos_token_id=fake_eos, pad_token_id=0,
    )
    state = serve_batch(
        engine2, [dict(prompt_ids=prompt, max_new_tokens=max_new, rng=jax.random.PRNGKey(1))]
    )[0]
    assert state.status == RequestStatus.completed
    assert state.num_generated == first + 1  # EOS counts as an emitted token
    assert state.tokens[-1] == fake_eos
    assert state.tokens == free_run.tokens[: first + 1]  # prefix unaffected by the stop
    assert engine2.pool.num_free == 1


def test_admission_under_full_pool_is_fcfs():
    config, model, params = _tiny_model()
    rs = np.random.RandomState(5)
    engine = ServingEngine(
        model, params, num_slots=1, max_len=32, prefill_bucket_multiple=8,
        eos_token_id=None, pad_token_id=0, max_waiting=8,
    )
    finish_order: list[int] = []
    states = []
    for i in range(3):
        states.append(
            engine.submit(
                prompt_ids=_random_prompt(rs, config, 4 + i),
                max_new_tokens=3,
                on_finish=lambda st, i=i: finish_order.append(i),
            )
        )
    # single slot: later requests wait in queue, never >1 running
    assert [s.status for s in states] == [RequestStatus.waiting] * 3
    while engine.has_work():
        engine.step()
        assert engine.pool.num_active <= 1
    assert finish_order == [0, 1, 2]
    assert engine.stats.admitted == 3 and engine.stats.completed == 3


def test_queue_full_rejection():
    config, model, params = _tiny_model()
    engine = ServingEngine(
        model, params, num_slots=1, max_len=32, prefill_bucket_multiple=8,
        eos_token_id=None, pad_token_id=0, max_waiting=2,
    )
    for _ in range(2):
        engine.submit(prompt_ids=[5, 6, 7], max_new_tokens=2)
    with pytest.raises(QueueFullError):
        engine.submit(prompt_ids=[5, 6, 7], max_new_tokens=2)
    assert engine.stats.rejected == 1
    engine.drain()
    assert engine.stats.completed == 2


def test_request_validation():
    config, model, params = _tiny_model()
    engine = ServingEngine(
        model, params, num_slots=1, max_len=16, prefill_bucket_multiple=8,
        eos_token_id=None, pad_token_id=0,
    )
    with pytest.raises(ValueError):
        engine.submit(prompt_ids=[], max_new_tokens=2)
    with pytest.raises(ValueError):
        engine.submit(prompt_ids=[1, 2, 3], max_new_tokens=0)
    with pytest.raises(ValueError):
        engine.submit(prompt_ids=[1] * 12, max_new_tokens=8)  # 12 + 8 > max_len=16
    with pytest.raises(ValueError):
        ServingEngine(model, params, num_slots=1, max_len=16, prefill_bucket_multiple=7)
    with pytest.raises(ValueError):
        # cache cannot exceed the model's position budget
        ServingEngine(model, params, num_slots=1, max_len=config.n_positions + 1)


def test_deadline_cancellation_waiting_and_running():
    config, model, params = _tiny_model()
    rs = np.random.RandomState(7)
    now = [0.0]
    engine = ServingEngine(
        model, params, num_slots=1, max_len=32, prefill_bucket_multiple=8,
        eos_token_id=None, pad_token_id=0, clock=lambda: now[0],
    )
    running = engine.submit(
        prompt_ids=_random_prompt(rs, config, 5), max_new_tokens=20, deadline_s=4.0
    )
    waiting = engine.submit(
        prompt_ids=_random_prompt(rs, config, 5), max_new_tokens=20, deadline_s=1.0
    )
    unconstrained = engine.submit(prompt_ids=_random_prompt(rs, config, 5), max_new_tokens=2)

    engine.step()  # admits `running` (slot 0); `waiting` queued behind it
    assert running.status == RequestStatus.running
    now[0] = 2.0  # waiting's deadline lapses while queued; running still inside budget
    engine.step()
    now[0] = 5.0  # running's deadline lapses mid-decode
    engine.drain()

    assert waiting.status == RequestStatus.cancelled and waiting.slot is None
    assert running.status == RequestStatus.cancelled
    assert 0 < running.num_generated < 20  # produced some tokens, then cut off
    assert unconstrained.status == RequestStatus.completed  # freed slot was reused
    assert engine.pool.num_free == 1
    assert engine.stats.cancelled == 2 and engine.stats.completed == 1


def test_serving_telemetry_records(tmp_path):
    from dolomite_engine_tpu.utils.telemetry import (
        RECORD_SCHEMA,
        Telemetry,
        install_telemetry,
        uninstall_telemetry,
    )

    config, model, params = _tiny_model()
    rs = np.random.RandomState(11)
    sink = tmp_path / "serving.jsonl"
    telemetry = Telemetry(sink_path=str(sink), rank=0)
    install_telemetry(telemetry)
    try:
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, prefill_bucket_multiple=8,
            eos_token_id=None, pad_token_id=0,
        )
        serve_batch(
            engine,
            [dict(prompt_ids=_random_prompt(rs, config, 4 + i), max_new_tokens=3) for i in range(3)],
        )
        telemetry.close()
    finally:
        uninstall_telemetry()

    records = [json.loads(line) for line in open(sink)]
    serving = [r for r in records if r["kind"] == "serving"]
    assert serving, "drain must emit a serving record"
    final = serving[-1]
    for field in RECORD_SCHEMA["serving"]:
        assert field in final, field
    assert final["queue_depth"] == 0 and final["slots_active"] == 0
    assert final["num_slots"] == 2
    assert final["counters"]["admitted"] == 3 and final["counters"]["completed"] == 3
    assert final["counters"]["decode_tokens"] + 3 == 9  # 3 requests x 3 tokens, 1 from prefill each
    # cross-module counters landed in the registry too
    assert telemetry.counters["serving_requests_admitted"] == 3
    assert telemetry.counters["serving_requests_completed"] == 3
    assert telemetry.counters["serving_prefill_tokens"] == sum(4 + i for i in range(3))


# ---------------------------------------------------------------------------- generate.py


def test_generate_engine_path_writes_jsonl(tmp_path, monkeypatch):
    """generate.generate() routes decoder-only datasets through the engine and keeps the
    legacy jsonl contract (dataset order, generated_text/num_generated_tokens keys)."""
    from dolomite_engine_tpu import generate as generate_module
    from dolomite_engine_tpu.arguments import InferenceArgs
    from dolomite_engine_tpu.data import get_datasets_list
    from dolomite_engine_tpu.enums import DatasetSplit, Mode
    from dolomite_engine_tpu.model_wrapper import ModelWrapperForFinetuning
    from dolomite_engine_tpu.model_wrapper import base as mw_base

    class _StubTokenizer:
        eos_token_id = 1
        pad_token_id = 2
        vocab_size = 2048

        def __len__(self):
            return self.vocab_size

        def decode(self, ids, skip_special_tokens=True):
            return " ".join(str(int(i)) for i in ids)

        def __call__(self, text, add_special_tokens=False):
            return {"input_ids": [3 + (hash(text) + i) % 100 for i in range(4)]}

    monkeypatch.setattr(
        mw_base.ModelWrapper,
        "_setup_tokenizer",
        lambda self, name, extra: setattr(self, "tokenizer", _StubTokenizer()),
    )

    config = get_dense_test_config("mqa", "rope")
    args = InferenceArgs(
        model_args=dict(model_class="AutoModelForCausalLM", pretrained_config=config.to_dict()),
        datasets=[
            dict(
                class_name="DebugDataset",
                data_name="debug",
                class_args=dict(num_examples=5, token_id=5),
                max_input_tokens=6,
                max_output_tokens=4,
            )
        ],
        generation_parameters=dict(batch_size=2, max_new_tokens=3, prompt_bucket_multiple=8),
        output_dir=str(tmp_path / "out"),
    )

    mode = Mode.inference
    wrapper = ModelWrapperForFinetuning(
        mode=mode,
        model_name=None,
        pretrained_config=config.to_dict(),
        model_class="AutoModelForCausalLM",
    )
    # unsharded init: the mesh-sharded init_params path fails at seed (logical-axis skew)
    params = wrapper.model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    datasets_list, _ = get_datasets_list(
        dataset_args_list=args.datasets,
        split=DatasetSplit.test,
        mode=mode,
        tokenizer=wrapper.tokenizer,
        is_encoder_decoder=False,
    )
    generate_module.generate(args, wrapper, params, datasets_list, mode)

    out_file = tmp_path / "out" / "output-debug.jsonl"
    assert out_file.is_file()
    lines = [json.loads(line) for line in open(out_file)]
    assert len(lines) == 5
    for line in lines:
        assert "generated_text" in line
        assert 0 < line["num_generated_tokens"] <= 3


def test_generation_parameters_bucket_validation():
    from dolomite_engine_tpu.arguments import GenerationParameters

    with pytest.raises(ValueError):
        GenerationParameters(batch_size=1, max_new_tokens=2, prompt_bucket_multiple=7)
    with pytest.raises(ValueError):
        GenerationParameters(batch_size=1, max_new_tokens=2, prompt_bucket_multiple=0)
    gp = GenerationParameters(batch_size=1, max_new_tokens=2, prompt_bucket_multiple=16)
    assert gp.prompt_bucket_multiple == 16
