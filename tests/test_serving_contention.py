"""Contention-aware scheduling tests: priority tiers, paged-KV preemption (swap and
drop-and-recompute), oversubscription, and multi-turn session retention.

The load-bearing invariants:

- a preempted-then-resumed request is token-for-token identical to an unpreempted run
  (greedy bit-exact) with paged + prefix + chunked — and with speculation and quantized
  kv_dtype active;
- swap-out/in is a raw byte copy: restored pages (and quantized scale rows) are
  identical to what was swapped out;
- the decode/verify/chunk programs never recompile through preempt/resume churn;
- the scheduler's tier-then-FCFS order is stable: re-enqueued preempted requests do not
  skip ahead of earlier same-tier arrivals, and never block a higher tier;
- session-pinned prefix pages survive LRU pressure while the session is live and become
  evictable once its TTL lapses; routers keep session -> replica affinity.

All model paths are unsharded tiny models (same conventions as tests/test_serving*.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dolomite_engine_tpu.generation_utils import generate_tokens
from dolomite_engine_tpu.models.gpt_dolomite import GPTDolomiteForCausalLM
from dolomite_engine_tpu.serving import (
    Request,
    RequestStatus,
    Scheduler,
    ServingEngine,
    TierSLO,
    serve_batch,
)

from .test_commons import get_dense_test_config

PAGE = 8


def _tiny_model():
    config = get_dense_test_config("gqa", "rope", normalization_function="rmsnorm")
    model = GPTDolomiteForCausalLM(config=config)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return config, model, params


def _random_prompt(rs, config, length):
    return list(map(int, rs.randint(3, config.vocab_size, length)))


_REFERENCE_CACHE: dict = {}


def _reference(model, params, config, prompt, rng_seed, max_new):
    """One-shot generate_tokens reference, memoized so parametrized modes sharing a
    workload don't pay the compile twice (rng is PRNGKey(rng_seed))."""
    key = (tuple(prompt), rng_seed, max_new)
    if key not in _REFERENCE_CACHE:
        ids = jnp.asarray([prompt], jnp.int32)
        out, _ = generate_tokens(
            model, params, ids, jnp.ones_like(ids), jax.random.PRNGKey(rng_seed),
            max_new_tokens=max_new, do_sample=False, eos_token_id=None,
            pad_token_id=config.pad_token_id,
        )
        _REFERENCE_CACHE[key] = [int(t) for t in np.asarray(out[0])]
    return _REFERENCE_CACHE[key]


def _contended_engine(model, config, params, preemption, **overrides):
    """Pool sized so one low-tier hog fits but a second worst-case request does not —
    admitting a high-tier request then REQUIRES preemption."""
    kwargs = dict(
        num_slots=2,
        max_len=32,
        prefill_bucket_multiple=8,
        eos_token_id=None,
        pad_token_id=config.pad_token_id,
        page_size=PAGE,
        num_pages=3 + 1 + 1,  # 3 pages = one hog's worst case, +1 spare, +trash
        preemption=preemption,
    )
    kwargs.update(overrides)
    return ServingEngine(model, params, **kwargs)


# ------------------------------------------------------------------- scheduler ordering


def test_scheduler_pops_tier_then_fcfs():
    scheduler = Scheduler(max_waiting=8)
    low_a = scheduler.submit(Request(prompt_ids=[1], max_new_tokens=1, priority=2))
    high = scheduler.submit(Request(prompt_ids=[2], max_new_tokens=1, priority=0))
    low_b = scheduler.submit(Request(prompt_ids=[3], max_new_tokens=1, priority=2))
    mid = scheduler.submit(Request(prompt_ids=[4], max_new_tokens=1, priority=1))
    assert scheduler.queue_depth_by_tier() == {0: 1, 1: 1, 2: 2}
    assert [scheduler.pop_next() for _ in range(4)] == [high, mid, low_a, low_b]
    assert scheduler.pop_next() is None


def test_scheduler_push_front_is_stable_tier_then_fcfs():
    """Regression (the PR's small fix): a re-enqueued preempted request must come back
    at its seq position WITHIN its tier — behind earlier same-tier arrivals, never in
    front of them (a naive global appendleft put the latest re-enqueue first), and a
    low-tier re-enqueue must never block a higher-tier head."""
    scheduler = Scheduler(max_waiting=8)
    low_a = scheduler.submit(Request(prompt_ids=[1], max_new_tokens=1, priority=2))
    low_b = scheduler.submit(Request(prompt_ids=[2], max_new_tokens=1, priority=2))
    assert scheduler.pop_next() is low_a and scheduler.pop_next() is low_b
    # both "running"; preempt low_a FIRST, then low_b (naive appendleft would now pop
    # low_b first) — seq order must win
    scheduler.push_front(low_a)
    scheduler.push_front(low_b)
    # a higher-tier arrival AFTER the re-enqueues still pops first
    high = scheduler.submit(Request(prompt_ids=[3], max_new_tokens=1, priority=0))
    assert scheduler.pop_next() is high
    assert scheduler.pop_next() is low_a  # earlier arrival first, not the last re-enqueue
    assert scheduler.pop_next() is low_b
    # rollback case: a popped head returns to the exact head, ahead of later arrivals
    mid_a = scheduler.submit(Request(prompt_ids=[4], max_new_tokens=1, priority=1))
    mid_b = scheduler.submit(Request(prompt_ids=[5], max_new_tokens=1, priority=1))
    head = scheduler.pop_next()
    assert head is mid_a
    scheduler.push_front(head)
    assert scheduler.pop_next() is mid_a and scheduler.pop_next() is mid_b


def test_scheduler_ttft_headroom():
    now = [0.0]
    scheduler = Scheduler(
        max_waiting=4, clock=lambda: now[0], tier_slos={0: TierSLO(ttft_target_s=2.0)}
    )
    tiered = scheduler.submit(Request(prompt_ids=[1], max_new_tokens=1, priority=0))
    untiered = scheduler.submit(Request(prompt_ids=[2], max_new_tokens=1, priority=1))
    now[0] = 1.5
    assert scheduler.ttft_headroom(tiered) == pytest.approx(0.5)
    assert scheduler.ttft_headroom(untiered) is None  # no target for tier 1
    now[0] = 3.0
    assert scheduler.ttft_headroom(tiered) == pytest.approx(-1.0)  # already missed


# ------------------------------------------------------------------- preempt -> resume


@pytest.mark.parametrize("mode", ["recompute", "swap"])
def test_preempt_resume_is_greedy_bit_exact(mode):
    """A high-tier arrival evicts the low-tier hog mid-decode; the hog resumes and both
    requests finish token-for-token identical to one-shot generate_tokens — with the
    paged pool, prefix cache, and chunked prefill all active."""
    config, model, params = _tiny_model()
    rs = np.random.RandomState(0)
    engine = _contended_engine(model, config, params, mode)
    low_prompt, hi_prompt = _random_prompt(rs, config, 10), _random_prompt(rs, config, 12)
    low_rng, hi_rng = jax.random.PRNGKey(11), jax.random.PRNGKey(12)

    low = engine.submit(prompt_ids=low_prompt, max_new_tokens=12, rng=low_rng, priority=2)
    for _ in range(4):
        engine.step()
    assert low.status == RequestStatus.running
    hi = engine.submit(prompt_ids=hi_prompt, max_new_tokens=8, rng=hi_rng, priority=0)
    engine.drain()

    assert low.preemptions >= 1 and low.status == RequestStatus.completed
    assert hi.status == RequestStatus.completed and hi.preemptions == 0
    assert engine.stats.preemptions == low.preemptions
    if mode == "swap":
        assert engine.stats.pages_swapped_out > 0
        assert engine.stats.pages_swapped_in == engine.stats.pages_swapped_out
    assert low.tokens == _reference(model, params, config, low_prompt, 11, 12)
    assert hi.tokens == _reference(model, params, config, hi_prompt, 12, 8)
    assert engine.decode_compiles == 1
    assert engine.pool.num_free == engine.pool.num_slots  # slots reclaimed
    assert len(engine._swap or []) == 0  # no payload leaked in the host pool


def test_admission_preemption_only_evicts_strictly_lower_tiers():
    """A same-tier arrival must WAIT (FCFS within the tier), not evict."""
    config, model, params = _tiny_model()
    rs = np.random.RandomState(3)
    engine = _contended_engine(model, config, params, "swap")
    first = engine.submit(
        prompt_ids=_random_prompt(rs, config, 10), max_new_tokens=12, priority=1
    )
    for _ in range(4):
        engine.step()
    assert first.status == RequestStatus.running
    peer = engine.submit(
        prompt_ids=_random_prompt(rs, config, 12), max_new_tokens=8, priority=1
    )
    engine.step()
    assert first.status == RequestStatus.running and first.preemptions == 0
    assert peer.status == RequestStatus.waiting
    engine.drain()
    assert engine.stats.preemptions == 0
    assert first.status == peer.status == RequestStatus.completed


def test_preempt_resume_with_speculation_and_quantized_kv():
    """Preemption under ngram speculation + int8 paged KV: the preempted run matches an
    UNPREEMPTED engine of the same configuration token-for-token (int8 pages are a
    tolerance-level format, so the engine-vs-engine comparison is the bit-exactness
    contract), and the verify step still compiles exactly once. Both preemption modes
    run against one shared baseline."""
    config, model, params = _tiny_model()
    rs = np.random.RandomState(5)
    # repetitive prompt so the n-gram drafter actually proposes
    phrase = _random_prompt(rs, config, 5)
    low_prompt = (phrase * 3)[:12]
    hi_prompt = _random_prompt(rs, config, 12)
    low_rng, hi_rng = jax.random.PRNGKey(21), jax.random.PRNGKey(22)
    kwargs = dict(speculate_ngram=True, draft_k=3, kv_dtype="int8")

    def run(mode: str):
        engine = _contended_engine(
            model, config, params, mode,
            **(kwargs if mode != "off" else {**kwargs, "num_pages": 12}),
        )
        low = engine.submit(prompt_ids=low_prompt, max_new_tokens=12, rng=low_rng, priority=2)
        for _ in range(4):
            engine.step()
        hi = engine.submit(prompt_ids=hi_prompt, max_new_tokens=8, rng=hi_rng, priority=0)
        engine.drain()
        return engine, low, hi

    baseline_engine, low_ref, hi_ref = run("off")
    assert low_ref.preemptions == 0 and baseline_engine.stats.preemptions == 0
    for mode in ("recompute", "swap"):
        engine, low, hi = run(mode)
        assert low.preemptions >= 1, mode
        assert low.tokens == low_ref.tokens, mode
        assert hi.tokens == hi_ref.tokens, mode
        assert engine.verify_compiles == 1 and engine.decode_compiles == 0
        assert engine.pool.num_free == engine.pool.num_slots


def test_swap_roundtrip_page_and_scale_byte_identity():
    """Swap-out then swap-in restores page bytes AND quantized scale rows exactly —
    compared lane-for-lane against a device snapshot taken before preemption."""
    config, model, params = _tiny_model()
    rs = np.random.RandomState(7)
    engine = _contended_engine(model, config, params, "swap", kv_dtype="int8")
    prompt = _random_prompt(rs, config, 10)
    state = engine.submit(prompt_ids=prompt, max_new_tokens=12, rng=jax.random.PRNGKey(1), priority=2)
    for _ in range(5):
        engine.step()
    assert state.status == RequestStatus.running
    slot = state.slot
    resident = int(engine.pool.lengths[slot])
    used = -(-resident // PAGE)
    old_pages = np.asarray(engine.pool.page_table[slot, :used])
    snapshot = [
        {name: np.asarray(array[old_pages]) for name, array in cache.items()}
        for cache in engine.pool.caches
    ]

    engine._preempt(state)
    assert state.status == RequestStatus.waiting and state.resume is not None
    assert state.resume.swapped and state.resume.resident == resident
    # the host payload is byte-identical to the device snapshot
    payload, parked = engine._swap._parked[state.request.request_id]
    assert parked == used
    for chunk, reference in zip(payload, snapshot):
        assert set(chunk) == set(reference)
        for name in reference:
            np.testing.assert_array_equal(chunk[name][:used], reference[name])

    # resume through the normal admission path, then compare the restored device pages
    popped = engine.scheduler.pop_next()
    assert popped is state
    assert engine._try_admit(state)
    assert state.status == RequestStatus.running
    new_pages = np.asarray(engine.pool.page_table[state.slot, :used])
    assert new_pages.size and all(int(p) != 0 for p in new_pages)
    for cache, reference in zip(engine.pool.caches, snapshot):
        for name in reference:
            np.testing.assert_array_equal(np.asarray(cache[name][new_pages]), reference[name])
    engine.drain()
    assert state.status == RequestStatus.completed


def test_compile_counts_survive_preemption_churn():
    """decode_compiles stays 1 and the chunk-fn cache stops growing once warm, through
    repeated preempt/resume cycles (the acceptance clause on compile invariance)."""
    config, model, params = _tiny_model()
    rs = np.random.RandomState(9)
    engine = _contended_engine(model, config, params, "swap", num_slots=3, oversubscribe_ratio=2.0)

    def churn():
        specs = [
            dict(
                prompt_ids=_random_prompt(rs, config, 8 + 2 * (i % 3)),
                max_new_tokens=10,
                priority=i % 3,
            )
            for i in range(6)
        ]
        serve_batch(engine, specs)

    churn()  # warm every program, including the preempt/resume paths
    assert engine.stats.preemptions > 0, "workload failed to trigger preemption"
    warm_chunks = engine.chunk_compiles
    before = engine.stats.preemptions
    churn()
    assert engine.stats.preemptions > before  # more churn actually happened
    assert engine.decode_compiles == 1
    assert engine.chunk_compiles == warm_chunks  # no new chunk variants after warmup


# ------------------------------------------------------------------- oversubscription


def test_oversubscribed_admission_and_reclamation_bit_exact():
    """ratio 2.0 admits beyond physical pages; decode-time reclamation (prefix evict +
    preempt) keeps every request correct and the pool accounting clean."""
    config, model, params = _tiny_model()
    rs = np.random.RandomState(13)
    engine = ServingEngine(
        model, params, num_slots=5, max_len=24, prefill_bucket_multiple=8,
        eos_token_id=None, pad_token_id=config.pad_token_id, page_size=PAGE,
        num_pages=8, preemption="swap", oversubscribe_ratio=2.0,
    )
    # two prompt lengths -> two reference compile buckets; distinct rng per request
    prompts = [_random_prompt(rs, config, 6 + 2 * (i % 2)) for i in range(8)]
    states = serve_batch(
        engine,
        [
            dict(prompt_ids=p, max_new_tokens=8, rng=jax.random.PRNGKey(300 + i), priority=i % 2)
            for i, p in enumerate(prompts)
        ],
    )
    assert engine.stats.peak_active > 2  # more hogs in flight than physically reservable
    assert engine.stats.preemptions > 0  # the pool really ran physically dry
    for i, (state, prompt) in enumerate(zip(states, prompts)):
        assert state.status == RequestStatus.completed
        if i % 2 == 1:  # the low-tier rows — the ones that get preempted — all checked
            assert state.tokens == _reference(model, params, config, prompt, 300 + i, 8)
    assert engine.decode_compiles == 1
    assert engine.pool.num_free == engine.pool.num_slots
    assert engine.pool._total_reserved == 0


def test_preemption_and_oversubscription_validation():
    config, model, params = _tiny_model()
    with pytest.raises(ValueError):
        ServingEngine(model, params, num_slots=1, max_len=16, preemption="sideways")
    with pytest.raises(ValueError):
        ServingEngine(model, params, num_slots=1, max_len=16, paged=False, preemption="swap")
    with pytest.raises(ValueError):
        # oversubscription without preemption is unsafe and rejected
        ServingEngine(model, params, num_slots=1, max_len=16, oversubscribe_ratio=1.5)
    with pytest.raises(ValueError):
        ServingEngine(model, params, num_slots=1, max_len=16, oversubscribe_ratio=0.5)
    with pytest.raises(ValueError):
        ServingEngine(
            model, params, num_slots=1, max_len=16, prefill_only=True, preemption="swap"
        )
    engine = ServingEngine(model, params, num_slots=1, max_len=16, prefill_bucket_multiple=8)
    with pytest.raises(ValueError):
        engine.submit(prompt_ids=[1, 2], max_new_tokens=2, priority=-1)

    from dolomite_engine_tpu.arguments import GenerationParameters

    with pytest.raises(ValueError):
        GenerationParameters(batch_size=1, max_new_tokens=2, preemption="both")
    with pytest.raises(ValueError):
        GenerationParameters(batch_size=1, max_new_tokens=2, oversubscribe_ratio=1.5)
    with pytest.raises(ValueError):
        GenerationParameters(
            batch_size=1, max_new_tokens=2, paged_kv_cache=False, preemption="swap"
        )
    with pytest.raises(ValueError):
        GenerationParameters(batch_size=1, max_new_tokens=2, priority=-1)
    with pytest.raises(ValueError):
        GenerationParameters(batch_size=1, max_new_tokens=2, session_ttl_s=0.0)
    ok = GenerationParameters(
        batch_size=1, max_new_tokens=2, preemption="recompute", oversubscribe_ratio=1.5
    )
    assert ok.oversubscribe_ratio == 1.5


# ------------------------------------------------------------------------- sessions


def test_session_pinned_pages_survive_lru_pressure_then_expire():
    config, model, params = _tiny_model()
    rs = np.random.RandomState(17)
    now = [0.0]
    engine = ServingEngine(
        model, params, num_slots=2, max_len=32, prefill_bucket_multiple=8,
        eos_token_id=None, pad_token_id=config.pad_token_id, page_size=PAGE,
        num_pages=10, session_ttl_s=60.0, clock=lambda: now[0],
    )
    session_prompt = _random_prompt(rs, config, 2 * PAGE)
    serve_batch(
        engine,
        [dict(prompt_ids=session_prompt, max_new_tokens=4, session_id="chat-1")],
    )
    assert engine.prefix.probe_len(session_prompt) >= PAGE
    assert engine.prefix.sessions_live == 1

    def flood():
        serve_batch(
            engine,
            [
                dict(prompt_ids=_random_prompt(rs, config, 2 * PAGE), max_new_tokens=4)
                for _ in range(6)
            ],
        )

    flood()  # admission evicts LRU prefix pages — the pinned chain must survive
    assert engine.prefix.probe_len(session_prompt) >= PAGE, "pinned pages were evicted"

    # a live follow-up turn refreshes the TTL and counts a session hit
    now[0] = 50.0
    follow_up = session_prompt + _random_prompt(rs, config, 4)
    serve_batch(
        engine, [dict(prompt_ids=follow_up, max_new_tokens=4, session_id="chat-1")]
    )
    assert engine.stats.session_hits == 1

    # TTL lapse: the pin is released and pressure evicts the chain
    now[0] = 50.0 + 61.0
    engine.step()  # session expiry runs at the step boundary
    assert engine.prefix.sessions_live == 0
    flood()
    assert engine.prefix.probe_len(session_prompt) == 0


def test_router_session_affinity_e2e():
    from dolomite_engine_tpu.serving.cluster import EngineReplica, Router, route_batch

    config, model, params = _tiny_model()
    rs = np.random.RandomState(19)

    def build(replica_id):
        return EngineReplica(
            replica_id,
            ServingEngine(
                model, params, num_slots=2, max_len=48, prefill_bucket_multiple=8,
                eos_token_id=None, pad_token_id=config.pad_token_id, page_size=PAGE,
            ),
        )

    router = Router([build(0), build(1)])
    turn_one = _random_prompt(rs, config, 2 * PAGE)
    states = route_batch(
        router, [dict(prompt_ids=turn_one, max_new_tokens=4, session_id="conv-9")]
    )
    first_replica = next(
        r for r in router.replicas if states[0].request.session_id and
        r.engine.stats.admitted > 0
    )
    # turn 2 embeds turn 1's reply; the session must route back to the same replica
    # and reuse its pinned prefix pages
    turn_two = turn_one + states[0].tokens + _random_prompt(rs, config, 4)
    states2 = route_batch(
        router, [dict(prompt_ids=turn_two, max_new_tokens=4, session_id="conv-9")]
    )
    assert str(states2[0].status) == "completed"
    assert first_replica.engine.stats.admitted == 2  # same replica served both turns
    assert router.stats.session_affinity_hits >= 1
    assert first_replica.engine.stats.prefix_hit_tokens >= PAGE  # pinned pages reused
    other = next(r for r in router.replicas if r is not first_replica)
    assert other.engine.stats.admitted == 0


# ------------------------------------------------------------------------- telemetry


def test_serving_record_carries_contention_fields(tmp_path):
    import json

    from dolomite_engine_tpu.utils.telemetry import (
        RECORD_SCHEMA,
        Telemetry,
        install_telemetry,
        uninstall_telemetry,
    )

    config, model, params = _tiny_model()
    rs = np.random.RandomState(23)
    sink = tmp_path / "contention.jsonl"
    telemetry = Telemetry(sink_path=str(sink), rank=0)
    install_telemetry(telemetry)
    try:
        engine = _contended_engine(
            model, config, params, "swap",
            tier_slos={0: TierSLO(ttft_target_s=2.0, itl_target_s=0.5)},
        )
        low = engine.submit(
            prompt_ids=_random_prompt(rs, config, 10), max_new_tokens=12,
            priority=2, session_id="sess-7",
        )
        for _ in range(4):
            engine.step()
        engine.submit(prompt_ids=_random_prompt(rs, config, 12), max_new_tokens=8, priority=0)
        engine.drain()
        telemetry.close()
        assert low.preemptions >= 1
    finally:
        uninstall_telemetry()

    records = [json.loads(line) for line in open(sink)]
    serving = [r for r in records if r["kind"] == "serving"]
    final = serving[-1]
    for field in RECORD_SCHEMA["serving"]:
        assert field in final, field
    assert final["preemptions"] >= 1
    assert final["pages_swapped_out"] > 0
    assert final["pages_swapped_in"] == final["pages_swapped_out"]
    assert final["sessions_live"] == 1
    tiers = final["tiers"]
    assert set(tiers) == {"0", "2"}
    assert tiers["2"]["preempted"] >= 1
    assert tiers["0"]["ttft_target_ms"] == 2000.0
    assert tiers["0"]["ttft_p99_ms"] is not None
    assert telemetry.counters["serving_preemptions"] >= 1
    assert telemetry.counters["serving_pages_swapped_out"] > 0
    # per-tier gauges were written (dynamic names, one per tier seen)
    assert any(name.startswith("serving/priority_queue_depth/tier") for name in telemetry.gauges)
    assert any(name.startswith("serving/ttft_p99_ms/tier") for name in telemetry.gauges)
