"""Dataset tooling tests: preprocess jsonl -> bin/idx -> merge -> read back.

Parity: reference `tests/data/megatron_data_test.py:17-60` covers builder round-trip + shard
merge; here the actual CLI tools under tools/megatron_dataset are exercised.
"""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parents[2]
TOOLS = REPO / "tools" / "megatron_dataset"


def _make_tokenizer(tmp_path) -> str:
    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    vocab = {"<unk>": 0, "<eos>": 1}
    vocab.update({f"w{i}": i for i in range(2, 100)})
    tok = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = Whitespace()
    tok_dir = tmp_path / "tok"
    tok_dir.mkdir()
    tok.save(str(tok_dir / "tokenizer.json"))
    json.dump(
        {"tokenizer_class": "PreTrainedTokenizerFast", "eos_token": "<eos>"},
        open(tok_dir / "tokenizer_config.json", "w"),
    )
    return str(tok_dir)


def _write_jsonl(path, docs):
    with open(path, "w") as f:
        for doc in docs:
            f.write(json.dumps({"text": doc}) + "\n")


def _run(script, *args):
    subprocess.run(
        [sys.executable, str(TOOLS / script), *args],
        check=True,
        capture_output=True,
        text=True,
        timeout=300,
    )


def test_preprocess_merge_roundtrip(tmp_path):
    from dolomite_engine_tpu.data.megatron.indexed_dataset import MMapIndexedDataset

    tok_dir = _make_tokenizer(tmp_path)
    docs_a = ["w2 w3 w4", "w5 w6"]
    docs_b = ["w6 w7 w8 w9"]
    _write_jsonl(tmp_path / "a.jsonl", docs_a)
    _write_jsonl(tmp_path / "b.jsonl", docs_b)

    _run(
        "preprocess_data.py",
        "--input", str(tmp_path / "a.jsonl"),
        "--tokenizer", tok_dir,
        "--output-prefix", str(tmp_path / "shard_a"),
        "--append-eod",
    )
    _run(
        "preprocess_data.py",
        "--input", str(tmp_path / "b.jsonl"),
        "--tokenizer", tok_dir,
        "--output-prefix", str(tmp_path / "shard_b"),
        "--append-eod",
    )

    ds_a = MMapIndexedDataset(str(tmp_path / "shard_a_text"))
    assert len(ds_a) == 2
    np.testing.assert_array_equal(ds_a[0], [2, 3, 4, 1])  # w2 w3 w4 <eos>
    np.testing.assert_array_equal(ds_a[1], [5, 6, 1])

    _run(
        "merge_data.py",
        "--input-prefixes", str(tmp_path / "shard_a_text"), str(tmp_path / "shard_b_text"),
        "--output-prefix", str(tmp_path / "merged"),
    )
    merged = MMapIndexedDataset(str(tmp_path / "merged"))
    assert len(merged) == 3
    np.testing.assert_array_equal(merged[0], [2, 3, 4, 1])
    np.testing.assert_array_equal(merged[2], [6, 7, 8, 9, 1])

    _run("iterate_preprocessed_data.py", "--path-prefix", str(tmp_path / "merged"))


def test_pt_to_safetensors(tmp_path):
    """tools/pt_to_safetensors.py: torch .bin checkpoint -> sharded safetensors + tokenizer."""
    import torch

    sys.path.insert(0, str(REPO / "tools"))
    from pt_to_safetensors import convert

    src = tmp_path / "ckpt"
    src.mkdir()
    state = {
        "transformer.wte.weight": torch.randn(8, 4),
        "lm_head.weight": torch.randn(8, 4, dtype=torch.bfloat16),
    }
    torch.save(state, src / "pytorch_model.bin")
    json.dump({"model_type": "gpt_dolomite"}, open(src / "config.json", "w"))

    dest = tmp_path / "st"
    convert(str(src), str(dest))

    from dolomite_engine_tpu.utils.safetensors import SafeTensorsWeightsManager

    mgr = SafeTensorsWeightsManager(str(dest))
    assert set(mgr) == set(state)
    np.testing.assert_array_equal(
        mgr.get_tensor("transformer.wte.weight"), state["transformer.wte.weight"].numpy()
    )
    got_bf16 = mgr.get_tensor("lm_head.weight")
    np.testing.assert_array_equal(
        got_bf16.view(np.uint16), state["lm_head.weight"].view(torch.uint16).numpy()
    )
    assert (dest / "config.json").is_file()
