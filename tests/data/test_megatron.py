"""Megatron pretraining data pipeline tests.

Mirrors the reference test strategy (`tests/data/megatron_data_test.py`: builder round-trip +
shard merge) and extends it: native C++ helpers vs numpy-fallback parity, GPTDataset index
determinism, blending ratios, sampler order/resume.
"""

import numpy as np
import pytest

from dolomite_engine_tpu.data.megatron import (
    GPTDataset,
    GPTDatasetConfig,
    MegatronBatchSampler,
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
    Split,
)
from dolomite_engine_tpu.data.megatron.blended_dataset import BlendedDataset
from dolomite_engine_tpu.data.megatron.native import (
    _build_sample_idx_numpy,
    build_blending_indices,
    build_sample_idx,
    compile_helpers,
)


def _write_dataset(path_prefix, documents, dtype=np.int32):
    builder = MMapIndexedDatasetBuilder(str(path_prefix) + ".bin", dtype=dtype)
    for doc in documents:
        builder.add_item(np.asarray(doc))
        builder.end_document()
    builder.finalize(str(path_prefix) + ".idx")


class TestIndexedDataset:
    def test_round_trip(self, tmp_path):
        rng = np.random.RandomState(0)
        docs = [rng.randint(0, 1000, size=rng.randint(1, 50)) for _ in range(20)]
        prefix = tmp_path / "ds"
        _write_dataset(prefix, docs)

        ds = MMapIndexedDataset(str(prefix))
        assert len(ds) == 20
        for i, doc in enumerate(docs):
            np.testing.assert_array_equal(ds[i], doc)
        np.testing.assert_array_equal(ds.sequence_lengths, [len(d) for d in docs])
        assert ds.document_indices[-1] == 20

    def test_get_window(self, tmp_path):
        prefix = tmp_path / "ds"
        _write_dataset(prefix, [np.arange(100)])
        ds = MMapIndexedDataset(str(prefix))
        np.testing.assert_array_equal(ds.get(0, offset=10, length=5), np.arange(10, 15))

    def test_merge_shards(self, tmp_path):
        docs_a = [np.arange(10), np.arange(5)]
        docs_b = [np.arange(7)]
        _write_dataset(tmp_path / "a", docs_a)
        _write_dataset(tmp_path / "b", docs_b)

        merged = MMapIndexedDatasetBuilder(str(tmp_path / "m") + ".bin")
        merged.add_index(str(tmp_path / "a"))
        merged.add_index(str(tmp_path / "b"))
        merged.finalize(str(tmp_path / "m") + ".idx")

        ds = MMapIndexedDataset(str(tmp_path / "m"))
        assert len(ds) == 3
        for i, doc in enumerate(docs_a + docs_b):
            np.testing.assert_array_equal(ds[i], doc)

    def test_uint16_dtype(self, tmp_path):
        prefix = tmp_path / "ds"
        _write_dataset(prefix, [np.arange(10)], dtype=np.uint16)
        ds = MMapIndexedDataset(str(prefix))
        assert ds.index.dtype == np.uint16
        np.testing.assert_array_equal(ds[0], np.arange(10))


class TestNativeHelpers:
    def test_native_compiles(self):
        assert compile_helpers(), "g++ helper build should succeed in this image"

    def test_sample_idx_native_vs_numpy(self):
        rng = np.random.RandomState(1)
        sizes = rng.randint(1, 40, size=50).astype(np.int32)
        doc_idx = np.tile(np.arange(50, dtype=np.int32), 3)
        rng.shuffle(doc_idx)
        tokens_per_epoch = int(sizes.sum())
        seq_length = 16
        num_epochs = 3

        native = build_sample_idx(
            sizes, doc_idx, seq_length, num_epochs, tokens_per_epoch, use_native=True
        )
        num_samples = (num_epochs * tokens_per_epoch - 1) // seq_length
        fallback = _build_sample_idx_numpy(sizes, doc_idx, seq_length, num_samples)
        np.testing.assert_array_equal(native, fallback)

    def test_sample_idx_int64_doc_idx(self):
        sizes = np.asarray([10, 20, 30], dtype=np.int32)
        doc_idx = np.asarray([2, 0, 1], dtype=np.int64)
        out = build_sample_idx(sizes, doc_idx, 8, 1, 60, use_native=True)
        expected = _build_sample_idx_numpy(sizes, doc_idx, 8, (60 - 1) // 8)
        np.testing.assert_array_equal(out, expected)
        assert out.dtype == np.int64

    def test_sample_idx_windows_cover_stream(self):
        """Each (doc, offset) pair must point at stream position i*seq_len."""
        sizes = np.asarray([5, 7, 3, 9], dtype=np.int32)
        doc_idx = np.asarray([3, 1, 0, 2], dtype=np.int32)
        seq_length = 4
        sample_idx = build_sample_idx(sizes, doc_idx, seq_length, 1, int(sizes.sum()))

        stream = np.concatenate([np.arange(sizes[d]) + 100 * d for d in doc_idx])
        cum = np.concatenate([[0], np.cumsum(sizes[doc_idx])])
        for i in range(sample_idx.shape[0]):
            d, off = sample_idx[i]
            assert cum[d] + off == i * seq_length

    def test_blending_indices_ratios(self):
        weights = [0.5, 0.3, 0.2]
        size = 1000
        ds_index, ds_sample_index = build_blending_indices(weights, size, use_native=True)
        counts = np.bincount(ds_index, minlength=3)
        np.testing.assert_allclose(counts / size, weights, atol=0.01)
        # per-dataset sample ids are consecutive starting at 0
        for d in range(3):
            np.testing.assert_array_equal(
                ds_sample_index[ds_index == d], np.arange(counts[d])
            )

    def test_blending_native_vs_numpy(self):
        weights = [0.7, 0.1, 0.2]
        a = build_blending_indices(weights, 500, use_native=True)
        b = build_blending_indices(weights, 500, use_native=False)
        np.testing.assert_array_equal(a[0], b[0])
        np.testing.assert_array_equal(a[1], b[1])


def _make_gpt_dataset(tmp_path, num_samples=40, seq_len=16, seed=1234, fim_rate=0.0, tok=None):
    rng = np.random.RandomState(42)
    docs = [rng.randint(0, 500, size=rng.randint(5, 60)) for _ in range(30)]
    prefix = tmp_path / "corpus"
    if not MMapIndexedDataset.exists(str(prefix)):
        _write_dataset(prefix, docs)
    indexed = MMapIndexedDataset(str(prefix))
    config = GPTDatasetConfig(
        random_seed=seed,
        sequence_length=seq_len,
        blend=[str(prefix)],
        split="100,0,0",
        path_to_cache=str(tmp_path / "cache"),
        fim_rate=fim_rate,
    )
    return GPTDataset(
        indexed_dataset=indexed,
        indexed_indices=np.arange(30, dtype=np.int32),
        num_samples=num_samples,
        index_split=Split.train,
        tokenizer=tok,
        config=config,
    )


class TestGPTDataset:
    def test_sample_shapes_and_determinism(self, tmp_path):
        ds = _make_gpt_dataset(tmp_path)
        assert len(ds) >= 40
        s0 = ds[0]["text"]
        assert s0.shape == (17,)
        assert s0.dtype == np.int64

        # rebuilding from cache gives identical samples
        ds2 = _make_gpt_dataset(tmp_path)
        for i in (0, 1, 17, len(ds) - 1):
            np.testing.assert_array_equal(ds[i]["text"], ds2[i]["text"])

    def test_windows_tile_the_shuffled_stream(self, tmp_path):
        """Unshuffled windows (shuffle_index inverted) concatenate to the document stream."""
        ds = _make_gpt_dataset(tmp_path)
        inverse = np.argsort(np.asarray(ds.shuffle_index))
        seq = ds.config.sequence_length
        first = ds[int(inverse[0])]["text"]
        second = ds[int(inverse[1])]["text"]
        # windows overlap by one token
        assert first[-1] == second[0]
        stream = np.concatenate(
            [np.asarray(ds.indexed_dataset[int(d)]) for d in np.asarray(ds.document_index)]
        )
        np.testing.assert_array_equal(first, stream[: seq + 1])
        np.testing.assert_array_equal(second, stream[seq : 2 * seq + 1])

    def test_different_seed_different_order(self, tmp_path):
        ds1 = _make_gpt_dataset(tmp_path, seed=1)
        ds2 = _make_gpt_dataset(tmp_path, seed=2)
        assert any(
            not np.array_equal(ds1[i]["text"], ds2[i]["text"]) for i in range(10)
        )


class _CharTokenizer:
    """Character-level fake tokenizer for FIM: token id = codepoint, sentinels up top."""

    eos_token_id = 0

    def decode(self, ids):
        return "".join(chr(int(i)) for i in ids)

    def encode(self, text, add_special_tokens=False):
        return [ord(c) for c in text]

    def convert_tokens_to_ids(self, tokens):
        return [100_001, 100_002, 100_003, 100_004][: len(tokens)]


class TestFIM:
    def test_fim_preserves_length_and_triggers(self, tmp_path):
        tok = _CharTokenizer()
        ds = _make_gpt_dataset(tmp_path, fim_rate=1.0, tok=tok)
        sample = ds[0]["text"]
        assert sample.shape == (17,)
        sentinels = {100_001, 100_002, 100_003}
        assert sentinels & set(sample.tolist()), "FIM sentinel tokens should appear"

    def test_fim_rate_zero_is_identity(self, tmp_path):
        ds_plain = _make_gpt_dataset(tmp_path, fim_rate=0.0)
        ds_fim0 = _make_gpt_dataset(tmp_path, fim_rate=0.0, tok=_CharTokenizer())
        np.testing.assert_array_equal(ds_plain[3]["text"], ds_fim0[3]["text"])


class TestBlendedDataset:
    def test_blend(self, tmp_path):
        datasets = []
        for name in ("x", "y"):
            sub = tmp_path / name
            sub.mkdir()
            datasets.append(_make_gpt_dataset(sub, num_samples=60))
        config = datasets[0].config
        blended = BlendedDataset(
            datasets=datasets, weights=[0.5, 0.5], size=100, config=config
        )
        assert len(blended) == 100
        item = blended[0]
        assert set(item.keys()) == {"dataset_id", "text"}
        counts = np.bincount([blended[i]["dataset_id"] for i in range(100)], minlength=2)
        np.testing.assert_allclose(counts / 100, [0.5, 0.5], atol=0.02)

    def test_out_of_bounds(self, tmp_path):
        ds = _make_gpt_dataset(tmp_path, num_samples=60)
        blended = BlendedDataset(datasets=[ds], weights=[1.0], size=50, config=ds.config)
        with pytest.raises(IndexError):
            blended[50]


class TestMegatronBatchSampler:
    def test_order_and_sharding(self):
        # 2 replicas, micro 3 -> global batch stride 6
        s0 = list(MegatronBatchSampler(24, 0, 3, num_replicas=2, rank=0))
        s1 = list(MegatronBatchSampler(24, 0, 3, num_replicas=2, rank=1))
        assert s0[0] == [0, 1, 2] and s1[0] == [3, 4, 5]
        assert s0[1] == [6, 7, 8] and s1[1] == [9, 10, 11]
        assert len(s0) == 4

    def test_resume_by_consumed_samples(self):
        full = list(MegatronBatchSampler(24, 0, 3, num_replicas=2, rank=0))
        resumed = list(MegatronBatchSampler(24, 12, 3, num_replicas=2, rank=0))
        assert resumed == full[2:]

    def test_drop_last(self):
        batches = list(MegatronBatchSampler(10, 0, 2, num_replicas=2, rank=0))
        assert all(len(b) == 2 for b in batches)
        assert len(batches) == 2  # 10 // 4 full global batches
