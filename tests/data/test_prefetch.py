"""Async input pipeline tests (ISSUE 5 tentpole, `data/prefetch.py`).

Covers: depth-0 synchronous equivalence (byte-identical batch sequence), async == sync
sequence, resume-exactness with a NON-EMPTY prefetch queue at checkpoint time (prefetcher
level and through the real `finetune.train` preemption path), worker-exception
re-raising at the consuming `next()`, the StallWatchdog firing through the prefetcher's
queue get, clean shutdown with a full queue, the restartable eval-pass wrapper, and the
acceptance criterion: with a deliberately slow loader, the steady-state `data` goodput
bucket in the JSONL sink at `prefetch_depth>=2` is <10%% of its depth-0 value.

Everything runs on unsharded pytree paths (the sharded-model construction path has the
known seed logical-axis skew)."""

import json
import threading
import time
from contextlib import contextmanager

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dolomite_engine_tpu import finetune
from dolomite_engine_tpu.arguments import TrainingArgs
from dolomite_engine_tpu.checkpointing import load_checkpoint_for_training
from dolomite_engine_tpu.data.prefetch import PrefetchingIterable, StepPrefetcher
from dolomite_engine_tpu.finetune import _stack_micro_batches
from dolomite_engine_tpu.train_utils import TrainState
from dolomite_engine_tpu.utils import (
    StallWatchdog,
    install_telemetry,
    request_preemption,
    reset_preemption,
    uninstall_preemption_handler,
    uninstall_telemetry,
)
from dolomite_engine_tpu.utils.telemetry import Telemetry


# --------------------------------------------------------------------------- harness


class _SeqLoader:
    """Deterministic resumable loader: micro-batch k is full((2, 2), k). The cursor
    advances monotonically across epochs (epoch = `n` batches), so every batch in an
    infinite stream is unique and the consumed sequence pins the loader position."""

    def __init__(self, n=4, sleep=0.0, fail_at=None):
        self.n = n
        self.sleep = sleep
        self.fail_at = fail_at
        self.cursor = 0

    def __iter__(self):
        for _ in range(self.n):
            if self.fail_at is not None and self.cursor == self.fail_at:
                raise RuntimeError("poisoned shard")
            if self.sleep:
                time.sleep(self.sleep)
            value = self.cursor
            self.cursor += 1
            yield {"x": np.full((2, 4), value, np.float32)}

    def __len__(self):
        return self.n

    def state_dict(self):
        return {"cursor": self.cursor}

    def load_state_dict(self, sd):
        self.cursor = sd["cursor"]


def _values(batches):
    """One scalar per consumed step batch (all elements of a batch are equal)."""
    return [int(np.asarray(b["x"]).flat[0]) for b in batches]


def _consume(prefetcher, steps):
    return [next(prefetcher) for _ in range(steps)]


def _make(loader, depth, micros=1, loop=True):
    return StepPrefetcher(
        loader,
        depth=depth,
        micros_per_step=micros,
        assemble_fn=_stack_micro_batches,
        loop=loop,
        description="test loader",
    )


# --------------------------------------------------------------------------- equivalence


def test_depth0_matches_manual_synchronous_loop():
    """depth=0 is the pre-prefetch loops verbatim: same micro order, same stacking."""
    prefetcher = _make(_SeqLoader(), depth=0, micros=2)
    got = _consume(prefetcher, 6)

    reference_loader = _SeqLoader()

    def infinite(loader):
        while True:
            yield from iter(loader)

    it = infinite(reference_loader)
    for batch in got:
        expected = _stack_micro_batches([next(it) for _ in range(2)])
        np.testing.assert_array_equal(np.asarray(batch["x"]), np.asarray(expected["x"]))
        assert batch["x"].shape == (2, 2, 4)  # [accum, micro...]


@pytest.mark.parametrize("micros", [1, 3])
def test_async_sequence_matches_depth0(micros):
    sync = _make(_SeqLoader(), depth=0, micros=micros)
    async_ = _make(_SeqLoader(), depth=3, micros=micros)
    try:
        for a, b in zip(_consume(sync, 8), _consume(async_, 8)):
            np.testing.assert_array_equal(np.asarray(a["x"]), np.asarray(b["x"]))
    finally:
        async_.close()


def test_finite_source_stop_iteration_propagates():
    prefetcher = _make(_SeqLoader(n=5), depth=2, micros=1, loop=False)
    try:
        assert _values(list(prefetcher)) == [0, 1, 2, 3, 4]
        with pytest.raises(StopIteration):
            next(prefetcher)  # stays exhausted
    finally:
        prefetcher.close()


# --------------------------------------------------------------------------- resume exactness


def test_resume_exact_with_nonempty_queue():
    """Tentpole: checkpoint while batches sit in the prefetch queue; the restored stream
    continues with exactly the first unconsumed batch — bit-for-bit the synchronous
    sequence, and the state survives the JSON round-trip checkpointing uses."""
    loader = _SeqLoader(sleep=0.002)
    prefetcher = _make(loader, depth=3, micros=2)
    try:
        consumed = _values(_consume(prefetcher, 3))
        deadline = time.time() + 5
        while prefetcher.queue_depth == 0 and time.time() < deadline:
            time.sleep(0.005)
        assert prefetcher.queue_depth > 0  # the loader ran AHEAD of consumption
        state = json.loads(json.dumps(prefetcher.state_dict()))
    finally:
        prefetcher.close()

    resumed = _make(_SeqLoader(sleep=0.002), depth=3, micros=2)
    resumed.load_state_dict(state)
    try:
        tail = _values(_consume(resumed, 5))
    finally:
        resumed.close()

    reference = _make(_SeqLoader(), depth=0, micros=2)
    expected = _values(_consume(reference, 8))
    assert consumed + tail == expected


def test_depth0_state_dict_roundtrip():
    prefetcher = _make(_SeqLoader(), depth=0, micros=2)
    head = _values(_consume(prefetcher, 2))
    state = prefetcher.state_dict()
    assert state["skip_batches"] == 1  # snapshot precedes the last consumed batch

    resumed = _make(_SeqLoader(), depth=0, micros=2)
    resumed.load_state_dict(state)
    tail = _values(_consume(resumed, 3))

    reference = _make(_SeqLoader(), depth=0, micros=2)
    assert head + tail == _values(_consume(reference, 5))


def test_load_accepts_legacy_bare_loader_state():
    """Checkpoints written before the prefetcher existed hold bare loader state."""
    prefetcher = _make(_SeqLoader(), depth=0, micros=1)
    prefetcher.load_state_dict({"cursor": 4})
    assert _values(_consume(prefetcher, 2)) == [4, 5]


def test_stateless_source_yields_empty_state():
    """Bare iterators (megatron pretrain loaders) wrap statelessly: resume rides the
    loop's consumed_samples metadata instead."""
    prefetcher = StepPrefetcher(iter([{"x": np.zeros((1,))}]), depth=0)
    assert prefetcher.state_dict() == {}


# --------------------------------------------------------------------------- failure transparency


def test_worker_exception_reraised_at_next():
    prefetcher = _make(_SeqLoader(n=8, fail_at=2), depth=2, micros=1, loop=False)
    try:
        assert _values(_consume(prefetcher, 2)) == [0, 1]
        with pytest.raises(RuntimeError, match="poisoned shard"):
            next(prefetcher)
        with pytest.raises(RuntimeError, match="poisoned shard"):
            next(prefetcher)  # the failure is sticky, not swallowed
    finally:
        prefetcher.close()


def test_stall_watchdog_fires_through_prefetcher():
    """A wedged worker looks exactly like a stalled dataloader: the watchdog bounds the
    prefetcher's queue get and aborts the run."""
    release = threading.Event()

    class _WedgedLoader(_SeqLoader):
        def __iter__(self):
            yield {"x": np.zeros((2, 4), np.float32)}
            release.wait(30)

    prefetcher = _make(_WedgedLoader(), depth=2, micros=1, loop=False)
    watchdog = StallWatchdog(prefetcher, timeout_seconds=0.3, description="train dataloader")
    try:
        next(watchdog)
        with pytest.raises(RuntimeError, match="train dataloader stalled"):
            next(watchdog)
    finally:
        release.set()
        watchdog.close()
        prefetcher.close()


def test_close_with_full_queue_stops_worker():
    prefetcher = _make(_SeqLoader(n=100), depth=1, micros=1)
    try:
        next(prefetcher)  # start the worker; it then blocks offering into the full queue
        deadline = time.time() + 5
        while prefetcher.queue_depth == 0 and time.time() < deadline:
            time.sleep(0.005)
    finally:
        prefetcher.close()
    assert not prefetcher._thread.is_alive()


# --------------------------------------------------------------------------- telemetry


def test_prefetch_telemetry_gauge_and_stall_counter(tmp_path):
    telemetry = Telemetry(sink_path=str(tmp_path / "sink.jsonl"))
    install_telemetry(telemetry)
    try:
        prefetcher = _make(_SeqLoader(sleep=0.02), depth=2, micros=1)
        try:
            _consume(prefetcher, 4)  # consumer outruns the 20ms/batch worker
        finally:
            prefetcher.close()
        assert "prefetch/queue_depth" in telemetry.gauges
        assert telemetry.counters.get("prefetch_stalls", 0) >= 1
    finally:
        uninstall_telemetry()
        telemetry.close()


# --------------------------------------------------------------------------- eval wrapper


def test_prefetching_iterable_restartable_passes():
    loader = _SeqLoader(n=5)
    wrapped = PrefetchingIterable(loader, depth=2)
    assert len(wrapped) == 5
    first = _values(list(wrapped))
    second = _values(list(wrapped))
    assert first == [0, 1, 2, 3, 4]
    assert second == [5, 6, 7, 8, 9]  # the cursor-advancing loader, second epoch

    # abandoning a pass mid-way tears the worker down and a fresh pass still works
    for i, _ in enumerate(wrapped):
        if i == 1:
            break
    assert len(_values(list(wrapped))) == 5


def test_prefetching_iterable_propagates_exceptions():
    wrapped = PrefetchingIterable(_SeqLoader(n=8, fail_at=1), depth=2)
    with pytest.raises(RuntimeError, match="poisoned shard"):
        list(wrapped)


# --------------------------------------------------------------------------- real-loop resume


class _RecordingPrefetcher(StepPrefetcher):
    """Records every consumed step batch and the queue depth at each state_dict call, so
    the loop-level test can assert the checkpoint was taken with a non-empty buffer."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.seen = []
        self.depth_at_save = []

    def __next__(self):
        batch = super().__next__()
        self.seen.append(int(np.asarray(batch["x"]).flat[0]))
        return batch

    def state_dict(self):
        self.depth_at_save.append(self.queue_depth)
        return super().state_dict()


class _Model:
    def loss(self, params, batch, rngs=None, train=True, fp8_state=None):
        return jnp.mean(params["w"] * batch["x"])


def _train_args(tmp_path, num_steps, load_path=None, prefetch_depth=2, log_interval=1):
    cfg = dict(
        model_args=dict(
            model_class="AutoModelForCausalLM",
            pretrained_config=dict(model_type="gpt_dolomite", vocab_size=8, n_positions=8,
                                   n_embd=4, n_layer=1, n_head=1),
        ),
        tuning_args=dict(tuning_method="full_finetuning"),
        training_parameters=dict(
            num_training_steps=num_steps,
            micro_batch_size=2,
            gradient_accumulation_steps=1,
            eval_during_training=False,
            prefetch_depth=prefetch_depth,
        ),
        datasets=[dict(class_name="DebugDataset", data_name="debug", class_args={})],
        save_args=dict(save_path=str(tmp_path / "ckpt"), save_interval=100),
        logging_args=dict(log_interval=log_interval),
        random_args=dict(seed=3),
    )
    if load_path is not None:
        cfg["load_args"] = dict(load_path=load_path)
    return TrainingArgs(**cfg)


def _fresh_state():
    params = {"w": jnp.ones((4,), jnp.float32), "b": jnp.zeros((2,), jnp.float32)}
    optimizer = optax.adam(1e-2)
    return (
        TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=optimizer.init(params)),
        optimizer,
    )


def _run_train(args, prefetcher, monkeypatch=None, preempt_at=None, state=None, start=0):
    if state is None:
        state, optimizer = _fresh_state()
    else:
        _, optimizer = _fresh_state()
    if preempt_at is not None:
        from dolomite_engine_tpu.train_utils import track_train_metrics as real_track

        def tracked(**kwargs):
            real_track(**kwargs)
            if kwargs["global_step"] == preempt_at:
                request_preemption()

        monkeypatch.setattr(finetune, "track_train_metrics", tracked)
    finetune.train(
        args, _Model(), state, optimizer, lambda step: 1e-2, prefetcher, None,
        experiments_tracker=None, starting_iteration=start,
    )


@pytest.fixture(autouse=True)
def _clean_preemption_state():
    reset_preemption()
    yield
    uninstall_preemption_handler()


def test_real_loop_preemption_resume_is_batch_exact(tmp_path, monkeypatch):
    """ISSUE acceptance: preempt the real finetune.train mid-run with a non-empty prefetch
    queue, restore from the checkpoint, and the consumed batch sequence across both runs
    is identical to one uninterrupted synchronous (depth 0) run."""
    # slow loader so the checkpoint reliably catches buffered-but-unconsumed batches
    run_a = _RecordingPrefetcher(
        _SeqLoader(sleep=0.01), depth=3, micros_per_step=1,
        assemble_fn=_stack_micro_batches, loop=True, description="train dataloader",
    )
    _run_train(_train_args(tmp_path, num_steps=9), run_a, monkeypatch, preempt_at=3)
    assert run_a.seen == [0, 1, 2]
    assert run_a.depth_at_save and run_a.depth_at_save[-1] > 0  # queue was non-empty

    # resume: a FRESH loader restored through the prefetcher, run to completion
    run_b = _RecordingPrefetcher(
        _SeqLoader(sleep=0.01), depth=3, micros_per_step=1,
        assemble_fn=_stack_micro_batches, loop=True, description="train dataloader",
    )
    args2 = _train_args(tmp_path, num_steps=9, load_path=str(tmp_path / "ckpt"))
    state, _ = _fresh_state()
    state, start, _, _ = load_checkpoint_for_training(args2, state, run_b)
    assert start == 3
    monkeypatch.setattr(finetune, "track_train_metrics", lambda **kwargs: None)
    _run_train(args2, run_b, state=state, start=start)

    # reference: one uninterrupted run on the synchronous path
    reference = _RecordingPrefetcher(
        _SeqLoader(), depth=0, micros_per_step=1,
        assemble_fn=_stack_micro_batches, loop=True, description="train dataloader",
    )
    _run_train(_train_args(tmp_path / "ref", num_steps=9, prefetch_depth=0), reference)

    assert run_a.seen + run_b.seen == reference.seen == list(range(9))


# --------------------------------------------------------------------------- goodput acceptance


def _read_sink(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


def test_steady_state_data_bucket_shrinks_under_prefetch(tmp_path, monkeypatch):
    """ISSUE acceptance: slow fake loader (50 ms/batch) + fixed per-step compute budget;
    at prefetch_depth>=2 the steady-state `data` goodput bucket in the JSONL sink drops
    to <10%% of its depth-0 value in the same test."""

    @contextmanager
    def slow_profiler_context(path, step):
        # a deterministic stand-in for the jitted step's wall time: 80 ms the prefetch
        # worker can overlap, independent of CI machine speed
        time.sleep(0.08)
        yield

    monkeypatch.setattr(finetune, "get_profiler_context", slow_profiler_context)

    def run(depth, where):
        prefetcher = StepPrefetcher(
            _SeqLoader(sleep=0.05), depth=depth, micros_per_step=1,
            assemble_fn=_stack_micro_batches, loop=True, description="train dataloader",
        )
        _run_train(_train_args(where, num_steps=10, prefetch_depth=depth, log_interval=5), prefetcher)
        records = _read_sink(where / "ckpt" / "telemetry" / "rank-00000.jsonl")
        windows = [r for r in records if r["kind"] == "window"]
        assert len(windows) == 2
        return windows[1]["goodput"]["data"]  # steps 6-10: past compile + queue warmup

    sync_data = run(0, tmp_path / "sync")
    async_data = run(2, tmp_path / "async")

    assert sync_data >= 0.2  # 5 steady steps x 50 ms actually measured on the sync path
    assert async_data < 0.1 * sync_data
