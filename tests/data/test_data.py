"""Data subsystem tests.

Parity: reference `tests/data/dataloader_test.py` (deterministic order/resume of
BlendedDistributedSampler simulated across world_size=8 in-process) + collate tests.
"""

import numpy as np
import pytest

from dolomite_engine_tpu.data.base import BlendedDatasets
from dolomite_engine_tpu.data.dataloader import ResumableDataLoader
from dolomite_engine_tpu.data.debug import DebugDataset
from dolomite_engine_tpu.data.sampler import BlendedDistributedSampler
from dolomite_engine_tpu.data.utils import collate_fn
from dolomite_engine_tpu.enums import DatasetSplit, LossMask, Mode


class _ListDataset:
    def __init__(self, n, offset=0, data_name="list"):
        self.examples = [{"input": [offset + i], "output": [offset + i]} for i in range(n)]
        self.data_name = data_name

    def __len__(self):
        return len(self.examples)

    def __getitem__(self, i):
        return self.examples[i]

    def state_dict(self):
        return {}

    def load_state_dict(self, sd):
        pass


def _blended(sizes=(10, 30)):
    datasets = [_ListDataset(n, offset=100 * i, data_name=f"d{i}") for i, n in enumerate(sizes)]
    return BlendedDatasets(datasets, DatasetSplit.train)


def test_sampler_rank_partition_covers_everything_once():
    """All ranks' samples together = one epoch worth (world_size=8, in-process rank loop)."""
    world = 8
    per_rank = []
    for rank in range(world):
        ds = _blended()
        sampler = BlendedDistributedSampler(
            ds, [1, 3], num_replicas=world, rank=rank, shuffle=True, seed=7
        )
        per_rank.append(list(iter(sampler)))

    lengths = {len(x) for x in per_rank}
    assert len(lengths) == 1
    total = sum(per_rank, [])
    assert len(total) == len(_blended())  # 40 examples, padded to multiple of 8 = 40


def test_sampler_deterministic_and_epoch_varies():
    ds = _blended()
    s1 = BlendedDistributedSampler(ds, [1, 3], 4, 0, shuffle=True, seed=3)
    s2 = BlendedDistributedSampler(ds, [1, 3], 4, 0, shuffle=True, seed=3)
    e0_a = list(iter(s1))
    e0_b = list(iter(s2))
    assert e0_a == e0_b
    e1 = list(iter(s1))  # epoch auto-incremented
    assert e1 != e0_a


def test_sampler_proportions():
    ds = _blended((10, 30))
    sampler = BlendedDistributedSampler(ds, [3, 1], 1, 0, shuffle=False, seed=0)
    idx = list(iter(sampler))
    from_d0 = sum(1 for i in idx if i < 10)
    from_d1 = len(idx) - from_d0
    assert from_d0 == 30 and from_d1 == 10  # 3:1 ratio over 40 total


def test_sampler_resume_replay():
    ds = _blended()
    sampler = BlendedDistributedSampler(ds, [1, 3], 2, 1, shuffle=True, seed=11)
    it = iter(sampler)
    consumed = [next(it) for _ in range(5)]
    state = sampler.state_dict()
    remaining_orig = list(it)

    fresh = BlendedDistributedSampler(_blended(), [1, 3], 2, 1, shuffle=True, seed=11)
    fresh.load_state_dict(state)
    remaining_resumed = list(iter(fresh))[: len(remaining_orig)]
    # replay positions the cursor; next epoch continues from same stream
    assert len(remaining_orig) == sampler.num_samples - 5


def test_resumable_dataloader_batching():
    ds = _blended((16, 16))
    sampler = BlendedDistributedSampler(ds, [1, 1], 1, 0, shuffle=False, seed=0)
    loader = ResumableDataLoader(ds, batch_size=4, sampler=sampler, collate_fn=None)
    batches = list(loader)
    assert len(batches) == 8 and all(len(b) == 4 for b in batches)
    assert "sampler" in loader.state_dict()


def test_collate_left_pads_with_eos():
    batch = [
        {"input": [5, 6, 7, 8], "output": [7, 8]},
        {"input": [9], "output": [9]},
    ]
    out = collate_fn(
        batch,
        mode=Mode.training,
        loss_mask=LossMask.output_only,
        eos_token_id=0,
        is_encoder_decoder=False,
        use_padding_free_transformer=False,
    )
    assert out["input_ids"].tolist() == [[5, 6, 7, 8], [0, 0, 0, 9]]
    assert out["attention_mask"].tolist() == [[1, 1, 1, 1], [0, 0, 0, 1]]
    # labels shifted: logits[t] predicts input[t+1]; only output tokens supervised
    assert out["labels"].tolist()[0] == [-100, 7, 8, -100]


def test_collate_padding_free_packs_documents():
    batch = [
        {"input": [5, 6, 7], "output": [6, 7]},
        {"input": [8, 9], "output": [9]},
    ]
    out = collate_fn(
        batch,
        mode=Mode.training,
        loss_mask=LossMask.output_only,
        eos_token_id=0,
        is_encoder_decoder=False,
        use_padding_free_transformer=True,
        pad_to_multiple=8,
    )
    assert out["input_ids"].shape == (1, 8)
    assert out["segment_ids"].tolist() == [[1, 1, 1, 2, 2, 0, 0, 0]]
    assert out["position_ids"].tolist() == [[0, 1, 2, 0, 1, 0, 0, 0]]
    labels = out["labels"].tolist()[0]
    assert labels[4] == -100  # no supervision across the doc boundary / padding
    assert labels[1] == 7  # predicts next token inside doc 1


def test_debug_dataset():
    class _Tok:
        eos_token_id = 0

    ds = DebugDataset(
        class_args={"num_examples": 12},
        split=DatasetSplit.train,
        mode=Mode.training,
        tokenizer=_Tok(),
        is_encoder_decoder=False,
        data_name="debug",
        input_format="__input__",
        output_format="__output__",
        max_input_tokens=8,
        max_output_tokens=8,
    )
    assert len(ds) == 12
    ex = ds[0]
    # max_output_tokens is reduced by 1 for the appended EOS, then +1 in the debug example
    assert len(ex["input"]) == 8 and len(ex["output"]) == 8
