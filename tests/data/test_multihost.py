"""Multi-host data sharding (scripts/pretrain_pod.sh contract): with process_count > 1, each
host must consume a disjoint 1/num_hosts share of every global batch, and the shares must
tile the same contiguous consumed-samples range the reference's Megatron sampler defines.

Parity: reference `scripts/pretrain.sh:14-21` launches one torchrun rank per GPU; here one
process per host feeds all local chips (data/megatron/__init__.py:86-100,
data/dataloader.py ShardedDataLoader). jax.process_count()/process_index() are monkeypatched
— the sampler/loader math is pure and needs no real second host.
"""

import json

import jax
import numpy as np
import pytest

from dolomite_engine_tpu.data.megatron import MMapIndexedDatasetBuilder
from dolomite_engine_tpu.data.megatron.sampler import MegatronBatchSampler


def test_sampler_partitions_global_batch():
    """Hosts' index lists are disjoint and tile [consumed, consumed + t*B) contiguously."""
    total, consumed, micro, hosts = 64, 8, 2, 4
    per_host = [
        list(
            MegatronBatchSampler(
                total_samples=total,
                consumed_samples=consumed,
                micro_batch_size=micro,
                num_replicas=hosts,
                rank=r,
            )
        )
        for r in range(hosts)
    ]

    steps = len(per_host[0])
    assert steps == (total - consumed) // (micro * hosts)
    for t in range(steps):
        global_batch = sorted(i for r in range(hosts) for i in per_host[r][t])
        start = consumed + t * micro * hosts
        assert global_batch == list(range(start, start + micro * hosts))
        # disjointness across hosts
        assert len({i for r in range(hosts) for i in per_host[r][t]}) == micro * hosts


def test_megatron_loader_respects_process_index(tmp_path, monkeypatch):
    """get_megatron_gpt_dataloaders with mocked process_count=2: the two hosts' first batches
    concatenate to exactly the single-host global batch (order preserved)."""
    from dolomite_engine_tpu.arguments import TrainingArgs
    from dolomite_engine_tpu.data import megatron as meg

    rng = np.random.RandomState(0)
    prefix = str(tmp_path / "corpus")
    builder = MMapIndexedDatasetBuilder(prefix + ".bin", dtype=np.uint16)
    for _ in range(200):
        builder.add_item(rng.randint(0, 128, size=rng.randint(10, 80)))
        builder.end_document()
    builder.finalize(prefix + ".idx")

    def _args(cache_dir):
        return TrainingArgs(
            model_args=dict(
                model_class="AutoModelForCausalLM",
                pretrained_config=dict(
                    model_type="gpt_dolomite", vocab_size=128, n_positions=64, n_embd=32,
                    n_layer=1, n_head=2, attention_head_type="mha",
                    position_embedding_type="rope", bos_token_id=0, eos_token_id=1,
                    pad_token_id=2,
                ),
            ),
            tuning_args=dict(tuning_method="pretraining"),
            training_parameters=dict(
                num_training_steps=4, micro_batch_size=4, gradient_accumulation_steps=1,
                eval_during_training=False,
            ),
            datasets=[
                dict(
                    class_name="MegatronDataset",
                    data_name="Megatron",
                    class_args=dict(
                        eval_steps=1, data_cache_path=str(cache_dir), data_path=[prefix],
                        split="100,0,0", sequence_length=32,
                    ),
                )
            ],
            save_args=dict(save_path=str(cache_dir) + "-ckpt", save_interval=4),
            random_args=dict(seed=7),
        )

    class _Tok:
        eos_token_id = 1

    def first_batches(num_hosts, cache_dir):
        batches = {}
        synced = []
        if num_hosts > 1:
            from jax.experimental import multihost_utils

            monkeypatch.setattr(
                multihost_utils, "sync_global_devices", lambda name: synced.append(name)
            )
        monkeypatch.setattr(jax, "process_count", lambda: num_hosts)
        for rank in range(num_hosts):
            monkeypatch.setattr(jax, "process_index", lambda r=rank: r)
            train, _, _ = meg.get_megatron_gpt_dataloaders(
                _args(cache_dir), _Tok(), consumed_samples=0, mesh=None
            )
            batches[rank] = next(train)["text"]
        return batches

    single = first_batches(1, tmp_path / "cache1")[0]
    two = first_batches(2, tmp_path / "cache2")

    # global micro batch = micro_batch_size * dp_world_size (8 virtual devices here);
    # each of the 2 hosts loads exactly half of it, in order
    global_rows = single.shape[0]
    assert two[0].shape[0] == global_rows // 2 and two[1].shape[0] == global_rows // 2
    np.testing.assert_array_equal(np.concatenate([two[0], two[1]], axis=0), single)


class _FakeLoader:
    """Deterministic stand-in for a ResumableDataLoader (dict batches, one None key)."""

    def __init__(self, n=3, batch=8, seq=6):
        self.n, self.batch, self.seq = n, batch, seq

    def __iter__(self):
        for i in range(self.n):
            yield {
                "input_ids": np.full((self.batch, self.seq), i, np.int32),
                "labels": np.full((self.batch, self.seq), 100 + i, np.int32),
                "position_ids": None,
            }

    def __len__(self):
        return self.n

    def state_dict(self):
        return {"cursor": 7}

    def load_state_dict(self, sd):
        self.loaded = sd


def test_dispatching_loader_single_process():
    """process_count=1 degenerate case: the broadcast is an identity and the yielded
    global arrays match the source batches exactly (incl. the None key and termination)."""
    from dolomite_engine_tpu.data.dataloader import DispatchingDataLoader
    from dolomite_engine_tpu.parallel.mesh import MeshManager

    MeshManager.destroy()
    MeshManager(data_parallel_sharding_world_size=4)
    try:
        mesh = MeshManager.get_mesh()
        loader = DispatchingDataLoader(_FakeLoader(), mesh)
        assert len(loader) == 3
        seen = list(loader)
        assert len(seen) == 3
        for i, batch in enumerate(seen):
            assert batch["position_ids"] is None
            np.testing.assert_array_equal(np.asarray(batch["input_ids"]), np.full((8, 6), i))
            np.testing.assert_array_equal(np.asarray(batch["labels"]), np.full((8, 6), 100 + i))
            assert batch["input_ids"].sharding.spec == jax.sharding.PartitionSpec(("dp", "fsdp"))
        assert loader.state_dict() == {"cursor": 7}
    finally:
        MeshManager.destroy()


def test_dispatching_loader_receiver_lockstep(monkeypatch):
    """Simulated 2-process run: a stubbed broadcast carries the source's buffers to a
    receiver built with local_loader=None (never touches a dataset); both sides yield
    identical batches and stop together."""
    from dolomite_engine_tpu.data import dataloader as dl
    from dolomite_engine_tpu.parallel.mesh import MeshManager

    MeshManager.destroy()
    MeshManager(data_parallel_sharding_world_size=4)
    try:
        mesh = MeshManager.get_mesh()

        # record from BEFORE construction: __init__ now broadcasts the loader length
        # eagerly, and the receiver must replay that collective too
        channel = []
        monkeypatch.setattr(
            dl.DispatchingDataLoader, "_broadcast", staticmethod(lambda t: (channel.append(t), t)[1])
        )
        source = dl.DispatchingDataLoader(_FakeLoader(), mesh)

        src_batches = list(source)

        # receiver: replay the recorded collective traffic in order
        replay = iter(channel)
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        monkeypatch.setattr(
            dl.DispatchingDataLoader, "_broadcast", staticmethod(lambda t: next(replay))
        )
        receiver = dl.DispatchingDataLoader(None, mesh)
        # the eager length broadcast makes len() correct BEFORE the first batch
        assert len(receiver) == 3
        rec_batches = list(receiver)
        assert len(receiver) == 3

        assert len(rec_batches) == len(src_batches) == 3
        for s, r in zip(src_batches, rec_batches):
            assert r["position_ids"] is None
            np.testing.assert_array_equal(np.asarray(s["input_ids"]), np.asarray(r["input_ids"]))
            np.testing.assert_array_equal(np.asarray(s["labels"]), np.asarray(r["labels"]))
        assert receiver.state_dict() == {}
    finally:
        MeshManager.destroy()


def test_dispatching_loader_rejects_unsupported_dtype():
    """An unsupported batch dtype must fail loudly, naming the key and dtype (not an
    opaque generator StopIteration)."""
    from dolomite_engine_tpu.data.dataloader import DispatchingDataLoader
    from dolomite_engine_tpu.parallel.mesh import MeshManager

    class _BadLoader(_FakeLoader):
        def __iter__(self):
            yield {"weights": np.ones((8, 6), np.float64)}

    MeshManager.destroy()
    MeshManager(data_parallel_sharding_world_size=4)
    try:
        loader = DispatchingDataLoader(_BadLoader(), MeshManager.get_mesh())
        with pytest.raises(ValueError, match="weights.*float64"):
            next(iter(loader))
    finally:
        MeshManager.destroy()


def test_dispatching_loader_int64_cast_and_overflow(monkeypatch):
    """int64 batches: broadcast_one_to_all silently downcasts int64->int32 under default
    x64-disabled JAX (ADVICE.md #1), so the sender casts explicitly after a range check —
    in-range values arrive as int32 bit-equal, out-of-range values fail loudly."""
    from dolomite_engine_tpu.data.dataloader import DispatchingDataLoader
    from dolomite_engine_tpu.parallel.mesh import MeshManager

    class _Int64Loader(_FakeLoader):
        def __iter__(self):
            yield {"ids": np.arange(48, dtype=np.int64).reshape(8, 6)}

    class _OverflowLoader(_FakeLoader):
        def __iter__(self):
            yield {"ids": np.full((8, 6), 2**40, np.int64)}

    MeshManager.destroy()
    MeshManager(data_parallel_sharding_world_size=4)
    try:
        mesh = MeshManager.get_mesh()
        batch = next(iter(DispatchingDataLoader(_Int64Loader(), mesh)))
        assert np.asarray(batch["ids"]).dtype == np.int32
        np.testing.assert_array_equal(
            np.asarray(batch["ids"]), np.arange(48).reshape(8, 6)
        )

        with pytest.raises(ValueError, match="ids.*int32 range"):
            next(iter(DispatchingDataLoader(_OverflowLoader(), mesh)))
    finally:
        MeshManager.destroy()


def test_dispatching_loader_rejects_excess_dims():
    """A batch array with more dims than the fixed-size header carries must raise a clear
    ValueError naming key and ndim, not an opaque numpy broadcast error (ADVICE.md #3)."""
    from dolomite_engine_tpu.data.dataloader import DispatchingDataLoader
    from dolomite_engine_tpu.parallel.mesh import MeshManager

    class _DeepLoader(_FakeLoader):
        def __iter__(self):
            yield {"deep": np.ones((4, 1, 1, 1, 1, 1, 2), np.int32)}

    MeshManager.destroy()
    MeshManager(data_parallel_sharding_world_size=4)
    try:
        loader = DispatchingDataLoader(_DeepLoader(), MeshManager.get_mesh())
        with pytest.raises(ValueError, match="deep.*ndim 7"):
            next(iter(loader))
    finally:
        MeshManager.destroy()
