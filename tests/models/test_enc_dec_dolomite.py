"""EncDecDolomite (seq2seq) tests.

Parity target: the reference finetunes `AutoModelForSeq2SeqLM` end-to-end
(`/root/reference/dolomite_engine/arguments.py:72-76`; encoder-decoder collate at
`data/utils.py:30-60`). Covered here: forward shapes, shift_right semantics, loss masking,
gradient flow through both stacks, collate integration, wrapper/model_class validation, and
a sharded finetuning train step on the virtual mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dolomite_engine_tpu.data.utils import collate_fn
from dolomite_engine_tpu.enums import LossMask, Mode
from dolomite_engine_tpu.models import EncDecDolomiteForSeq2SeqLM, config_from_dict
from dolomite_engine_tpu.models.config import EncDecDolomiteConfig
from dolomite_engine_tpu.models.enc_dec_dolomite import shift_right
from dolomite_engine_tpu.ops.loss import IGNORE_INDEX


def _config(**kwargs) -> EncDecDolomiteConfig:
    defaults = dict(
        vocab_size=256,
        n_positions=128,
        n_embd=32,
        n_layer=2,
        n_encoder_layer=2,
        n_head=4,
        num_key_value_heads=2,
        attention_head_type="gqa",
        position_embedding_type="rope",
        activation_function="swiglu",
        normalization_function="rmsnorm",
        add_bias=False,
        resid_pdrop=0.0,
        embd_pdrop=0.0,
        attn_pdrop=0.0,
        bos_token_id=0,
        eos_token_id=1,
        pad_token_id=2,
    )
    defaults.update(kwargs)
    return EncDecDolomiteConfig(**defaults)


def _batch(B=2, S_enc=24, S_dec=16, vocab=256, seed=0):
    rs = np.random.RandomState(seed)
    input_ids = rs.randint(3, vocab, size=(B, S_enc)).astype(np.int32)
    attention_mask = np.ones((B, S_enc), np.int32)
    attention_mask[0, :5] = 0  # left padding on row 0
    labels = rs.randint(3, vocab, size=(B, S_dec)).astype(np.int32)
    labels[1, -4:] = IGNORE_INDEX  # right padding on row 1
    return jnp.asarray(input_ids), jnp.asarray(attention_mask), jnp.asarray(labels)


def test_shift_right():
    labels = jnp.asarray([[7, 8, IGNORE_INDEX]])
    out = shift_right(labels, start_token_id=0, pad_token_id=2)
    np.testing.assert_array_equal(np.asarray(out), [[0, 7, 8]])


def test_forward_shapes_and_loss_finite():
    config = _config()
    model = EncDecDolomiteForSeq2SeqLM(config=config)
    input_ids, attention_mask, labels = _batch()
    params = model.init(
        jax.random.PRNGKey(0), input_ids, attention_mask=attention_mask, labels=labels
    )
    out = model.apply(params, input_ids, attention_mask=attention_mask, labels=labels)
    assert out.logits.shape == (2, 16, config.vocab_size)
    assert out.encoder_hidden_states.shape == (2, 24, config.n_embd)
    assert np.isfinite(float(out.loss))


def test_loss_masks_ignore_index_positions():
    """The model's loss must equal a manual masked CE over the returned logits: mean of
    -log_softmax[label] over positions where labels != IGNORE_INDEX, nothing else."""
    config = _config()
    model = EncDecDolomiteForSeq2SeqLM(config=config)
    input_ids, attention_mask, labels = _batch()
    params = model.init(
        jax.random.PRNGKey(0), input_ids, attention_mask=attention_mask, labels=labels
    )
    out = model.apply(params, input_ids, attention_mask=attention_mask, labels=labels)

    logp = jax.nn.log_softmax(out.logits.astype(jnp.float32), axis=-1)
    mask = np.asarray(labels) != IGNORE_INDEX
    safe = np.where(mask, np.asarray(labels), 0)
    token_logp = np.take_along_axis(np.asarray(logp), safe[..., None], axis=-1)[..., 0]
    expected = -(token_logp * mask).sum() / mask.sum()
    np.testing.assert_allclose(float(out.loss), expected, rtol=1e-5)


def test_gradients_flow_through_both_stacks():
    config = _config()
    model = EncDecDolomiteForSeq2SeqLM(config=config)
    input_ids, attention_mask, labels = _batch(seed=1)
    params = model.init(
        jax.random.PRNGKey(0), input_ids, attention_mask=attention_mask, labels=labels
    )

    def loss_fn(p):
        return model.apply(p, input_ids, attention_mask=attention_mask, labels=labels).loss

    grads = jax.grad(lambda p: loss_fn(p))(params)["params"]
    flat = jax.tree_util.tree_flatten_with_path(grads)[0]
    zero_paths = [
        jax.tree_util.keystr(path) for path, g in flat if float(jnp.abs(g).max()) == 0.0
    ]
    assert not zero_paths, f"zero gradients at {zero_paths}"
    # cross-attention and encoder params exist and receive gradient
    names = [jax.tree_util.keystr(p) for p, _ in flat]
    assert any("cross_attn" in n for n in names)
    assert any("encoder" in n for n in names)


def test_encoder_mask_respected():
    """Masked encoder positions must not influence the decoder output."""
    config = _config()
    model = EncDecDolomiteForSeq2SeqLM(config=config)
    input_ids, attention_mask, labels = _batch(seed=2)
    params = model.init(
        jax.random.PRNGKey(0), input_ids, attention_mask=attention_mask, labels=labels
    )
    out_a = model.apply(params, input_ids, attention_mask=attention_mask, labels=labels)
    # scramble the masked (padding) encoder tokens of row 0
    scrambled = input_ids.at[0, :5].set(99)
    out_b = model.apply(params, scrambled, attention_mask=attention_mask, labels=labels)
    np.testing.assert_allclose(
        np.asarray(out_a.logits[0]), np.asarray(out_b.logits[0]), atol=1e-5
    )


def test_collate_encoder_decoder_roundtrip():
    batch = [
        {"input": [5, 6, 7], "output": [8, 9]},
        {"input": [5], "output": [8, 9, 10]},
    ]
    out = collate_fn(
        batch,
        mode=Mode.training,
        loss_mask=LossMask.output_only,
        eos_token_id=1,
        is_encoder_decoder=True,
        use_padding_free_transformer=False,
    )
    assert out["input_ids"].shape == (2, 3)
    assert out["attention_mask"].tolist() == [[1, 1, 1], [0, 0, 1]]
    # unshifted decoder targets, IGNORE_INDEX right-padded (the model shifts internally)
    assert out["labels"].tolist() == [[8, 9, IGNORE_INDEX], [8, 9, 10]]


def test_wrapper_validates_model_class():
    from dolomite_engine_tpu.model_wrapper.base import ModelWrapper

    with pytest.raises(ValueError, match="model_class"):
        ModelWrapper(
            mode=Mode.training,
            pretrained_config=dict(_config().to_dict()),
            model_class="AutoModelForCausalLM",
        )
    with pytest.raises(ValueError, match="model_class"):
        ModelWrapper(
            mode=Mode.training,
            pretrained_config=dict(model_type="gpt_dolomite", vocab_size=128, n_positions=64,
                                   n_embd=32, n_layer=2, n_head=4),
            model_class="AutoModelForSeq2SeqLM",
        )


def test_sharded_finetuning_train_step(eight_devices):
    """Full seq2seq finetuning step (ZeRO-3) on the virtual 8-device mesh: loss finite and
    decreasing over a few steps on a fixed batch."""
    from dolomite_engine_tpu.distributed import create_sharded_train_state
    from dolomite_engine_tpu.enums import LRDecaySchedule
    from dolomite_engine_tpu.model_wrapper.pretraining import ModelWrapperForFinetuning
    from dolomite_engine_tpu.optimization import get_optimizer, get_scheduler
    from dolomite_engine_tpu.parallel.mesh import MeshManager, named_sharding
    from dolomite_engine_tpu.train_utils import make_train_step

    MeshManager()
    mesh = MeshManager.get_mesh()
    try:
        wrapper = ModelWrapperForFinetuning(
            mode=Mode.training,
            pretrained_config=dict(_config().to_dict()),
            model_class="AutoModelForSeq2SeqLM",
            dtype="fp32",
            zero_stage=3,
        )
        sched = get_scheduler(2, 0, None, 20, LRDecaySchedule.cosine, 0.1, base_lr=1e-3)
        opt = get_optimizer(
            "TorchAdamW", {"weight_decay": 0.1, "betas": (0.9, 0.95), "eps": 1e-10}, sched
        )
        state, _ = create_sharded_train_state(wrapper, opt, mesh, jax.random.PRNGKey(0))

        input_ids, attention_mask, labels = _batch(B=8, seed=3)

        def loss_fn(params, micro, rng):
            return wrapper.loss(params, micro, train=True)

        step = jax.jit(make_train_step(loss_fn, opt, gradient_accumulation_steps=1),
                       donate_argnums=0)
        batch = {
            "input_ids": jnp.asarray(input_ids),
            "attention_mask": jnp.asarray(attention_mask),
            "labels": jnp.asarray(labels),
        }
        with mesh:
            sharded = {
                k: jax.device_put(v[None], named_sharding(None, ("dp", "fsdp")))
                for k, v in batch.items()
            }
            losses = []
            for i in range(4):
                state, metrics = step(state, sharded, jax.random.PRNGKey(i))
                losses.append(float(metrics["loss"]))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0], losses
    finally:
        MeshManager.destroy()


def test_seq2seq_generation_shapes():
    """Jitted encoder-decoder greedy decode: static shapes, pad-after-eos semantics."""
    from dolomite_engine_tpu.generation_utils import make_generate_fn

    config = _config()
    model = EncDecDolomiteForSeq2SeqLM(config=config)
    input_ids, attention_mask, labels = _batch(seed=4)
    params = model.init(
        jax.random.PRNGKey(0), input_ids, attention_mask=attention_mask, labels=labels
    )
    fn = make_generate_fn(
        model, is_encoder_decoder=True, max_new_tokens=6, eos_token_id=config.eos_token_id,
        pad_token_id=config.pad_token_id, decoder_start_token_id=config.decoder_start_token_id,
    )
    generated, num_generated = fn(params, input_ids, attention_mask, jax.random.PRNGKey(1))
    generated, num_generated = np.asarray(generated), np.asarray(num_generated)
    assert generated.shape == (2, 6)
    assert ((1 <= num_generated) & (num_generated <= 6)).all()
    for row, n in zip(generated, num_generated):
        if n < 6:
            assert row[n - 1] == config.eos_token_id
            assert (row[n:] == config.pad_token_id).all()


@pytest.mark.parametrize(
    "add_bias,normalization", [(False, "rmsnorm"), (True, "layernorm")]
)
def test_save_pretrained_roundtrip(tmp_path, add_bias, normalization):
    """save_pretrained -> safetensors -> load_pretrained_params reproduces identical logits
    (the family's own flat-QKV layout; no foreign checkpoint to match). Parametrized so the
    bias + layernorm-bias converter branches are exercised, not just the bias-free path."""
    from dolomite_engine_tpu.hf_interop.weights import (
        params_to_state_dict,
        state_dict_to_params,
    )
    from dolomite_engine_tpu.utils.safetensors import SafeTensorsWeightsManager

    config = _config(add_bias=add_bias, normalization_function=normalization)
    model = EncDecDolomiteForSeq2SeqLM(config=config)
    input_ids, attention_mask, labels = _batch(seed=6)
    params = model.init(
        jax.random.PRNGKey(0), input_ids, attention_mask=attention_mask, labels=labels
    )["params"]

    sd = params_to_state_dict(config, params)
    SafeTensorsWeightsManager.save_state_dict(sd, str(tmp_path))
    loaded = state_dict_to_params(config, SafeTensorsWeightsManager(str(tmp_path)))

    ref = model.apply({"params": params}, input_ids, attention_mask=attention_mask,
                      labels=labels)
    out = model.apply({"params": loaded}, input_ids, attention_mask=attention_mask,
                      labels=labels)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref.logits), atol=1e-6)
