"""MoEDolomite numerical tests.

Parity: reference `tests/hf_models/single_gpu/dolomite_moe_test.py` (attention-impl matrix) and
`scattermoe_test.py:15` (scatter vs eager parity). Here "scatter" = ragged_dot grouped GEMM.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dolomite_engine_tpu.models.moe_dolomite import MoEDolomiteForCausalLM, SparseMoE
from dolomite_engine_tpu.ops.moe import (
    combine_weights,
    experts_eager,
    experts_ragged,
    load_balancing_loss,
    route,
)

from ..test_commons import assert_allclose, get_moe_test_config, get_dummy_inputs


def test_route_softmax_over_selected():
    logits = jnp.asarray(np.random.RandomState(0).randn(8, 6).astype(np.float32))
    weights, selected = route(logits, 2)
    top, idx = jax.lax.top_k(logits, 2)
    expected = jax.nn.softmax(top, axis=-1)
    assert_allclose(weights, expected, atol=1e-6, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(selected), np.asarray(idx))
    assert_allclose(jnp.sum(weights, axis=-1), np.ones(8), atol=1e-6, rtol=1e-6)


def test_eager_matches_per_token_loop():
    rs = np.random.RandomState(1)
    T, d, f, E, k = 10, 8, 12, 4, 2
    x = jnp.asarray(rs.randn(T, d).astype(np.float32))
    w1 = jnp.asarray(rs.randn(E, d, f).astype(np.float32) * 0.1)
    b1 = jnp.asarray(rs.randn(E, f).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rs.randn(E, f, d).astype(np.float32) * 0.1)
    b2 = jnp.asarray(rs.randn(E, d).astype(np.float32) * 0.1)
    logits = jnp.asarray(rs.randn(T, E).astype(np.float32))
    weights, selected = route(logits, k)

    combine = combine_weights(weights, selected, E)
    out = experts_eager(x, combine, w1, b1, w2, b2, jax.nn.gelu)

    expected = np.zeros((T, d), np.float32)
    for t in range(T):
        for j in range(k):
            e = int(selected[t, j])
            h = jax.nn.gelu(x[t] @ w1[e] + b1[e])
            expected[t] += float(weights[t, j]) * np.asarray(h @ w2[e] + b2[e])
    assert_allclose(out, expected, atol=1e-4, rtol=1e-4)


def test_ragged_matches_eager():
    rs = np.random.RandomState(2)
    T, d, f, E, k = 33, 16, 24, 8, 2
    x = jnp.asarray(rs.randn(T, d).astype(np.float32))
    w1 = jnp.asarray(rs.randn(E, d, f).astype(np.float32) * 0.1)
    b1 = jnp.asarray(rs.randn(E, f).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rs.randn(E, f, d).astype(np.float32) * 0.1)
    b2 = jnp.asarray(rs.randn(E, d).astype(np.float32) * 0.1)
    logits = jnp.asarray(rs.randn(T, E).astype(np.float32))
    weights, selected = route(logits, k)

    eager = experts_eager(
        x, combine_weights(weights, selected, E), w1, b1, w2, b2, jax.nn.gelu
    )
    ragged = experts_ragged(x, weights, selected, w1, b1, w2, b2, jax.nn.gelu, E)
    assert_allclose(ragged, eager, atol=1e-4, rtol=1e-4)


def test_load_balancing_loss_uniform_is_one():
    # perfectly uniform router -> loss == 1 (Switch normalization: E * E * (1/E) * (1/E) * k... )
    T, E, k = 64, 4, 2
    logits = jnp.zeros((T, E))
    loss = load_balancing_loss(logits, E, k)
    # uniform: tokens_per_expert rows sum to k/E per [k,E] row pair; prob = 1/E
    # loss = E * sum_{k,E} ( (top-k tie-broken assignment fraction) * 1/E )
    # with ties jax.lax.top_k picks lowest indices: still total mass k, so loss = k/E * E = ...
    assert np.isfinite(float(loss))
    # non-uniform router must have larger loss than a near-uniform random one
    rs = np.random.RandomState(3)
    near_uniform = jnp.asarray(rs.randn(T, E).astype(np.float32) * 0.01)
    collapsed = jnp.asarray(np.tile([10.0, 0, 0, 0], (T, 1)).astype(np.float32))
    assert float(load_balancing_loss(collapsed, E, k)) > float(
        load_balancing_loss(near_uniform, E, k)
    )


@pytest.mark.parametrize("moe_implementation", ["eager", "scatter"])
def test_model_forward_and_loss(moe_implementation):
    config = get_moe_test_config("gqa", "rope")
    model = MoEDolomiteForCausalLM(config=config, moe_implementation=moe_implementation)
    ids, mask = get_dummy_inputs(config)
    params = model.init(jax.random.PRNGKey(0), ids)
    out = model.apply(params, ids, attention_mask=mask, compute_loss=True)
    assert out.logits.shape == (*ids.shape, config.vocab_size)
    assert np.isfinite(float(out.loss))
    assert out.aux_loss is not None and np.isfinite(float(out.aux_loss))
    # aux loss is part of total loss
    out_no_aux = model.apply(params, ids, attention_mask=mask)
    assert out_no_aux.loss is None


def test_scatter_eager_model_parity():
    config = get_moe_test_config("mqa", "rope")
    eager_model = MoEDolomiteForCausalLM(config=config, moe_implementation="eager")
    scatter_model = MoEDolomiteForCausalLM(config=config, moe_implementation="scatter")
    ids, _ = get_dummy_inputs(config, padded=False)
    params = eager_model.init(jax.random.PRNGKey(0), ids)
    out_e = eager_model.apply(params, ids)
    out_s = scatter_model.apply(params, ids)
    assert_allclose(out_s.logits, out_e.logits, atol=2e-4, rtol=2e-4)


def test_aux_loss_masks_padding():
    """Padded positions must not influence router statistics (improvement over the reference,
    which calls HF load_balancing_loss_func without attention_mask)."""
    rs = np.random.RandomState(7)
    T, E, k = 16, 4, 2
    logits = jnp.asarray(rs.randn(T, E).astype(np.float32))
    mask = jnp.asarray([1] * 12 + [0] * 4)
    masked = load_balancing_loss(logits, E, k, token_mask=mask)
    only_valid = load_balancing_loss(logits[:12], E, k)
    assert_allclose(masked, only_valid, atol=1e-6, rtol=1e-6)


def test_aux_loss_zero_coef_skipped():
    config = get_moe_test_config("mqa", "rope", router_aux_loss_coef=0.0)
    model = MoEDolomiteForCausalLM(config=config, moe_implementation="eager")
    ids, _ = get_dummy_inputs(config, padded=False)
    params = model.init(jax.random.PRNGKey(0), ids)
    out = model.apply(params, ids, compute_loss=True)
    assert out.aux_loss is None
    assert np.isfinite(float(out.loss))


def test_kv_cache_decode():
    config = get_moe_test_config("gqa", "rope")
    model = MoEDolomiteForCausalLM(config=config, moe_implementation="eager")
    rs = np.random.RandomState(5)
    ids = jnp.asarray(rs.randint(0, config.vocab_size, (2, 10)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), ids)

    full = model.apply(params, ids)
    caches = model.init_kv_caches(2, 10)
    prefill = model.apply(params, ids[:, :6], kv_caches=caches, cache_index=jnp.zeros((), jnp.int32))
    logits = [prefill.logits]
    caches = prefill.kv_caches
    for t in range(6, 10):
        step = model.apply(
            params, ids[:, t : t + 1], kv_caches=caches, cache_index=jnp.asarray(t, jnp.int32)
        )
        caches = step.kv_caches
        logits.append(step.logits)
    assert_allclose(jnp.concatenate(logits, axis=1), full.logits, atol=3e-4, rtol=3e-4)
