"""GPTCrossLayer tests.

Parity: reference `tests/hf_models/single_gpu/gpt_crosslayer_test.py` (attention-impl matrix)
and the dolomite->crosslayer conversion (utils.py) — with the identity sharing pattern the
converted model must match the original exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dolomite_engine_tpu.models import (
    GPTCrossLayerForCausalLM,
    convert_gpt_dolomite_to_gpt_crosslayer,
)
from dolomite_engine_tpu.models.config import GPTCrossLayerConfig
from dolomite_engine_tpu.models.gpt_crosslayer import group_layout
from dolomite_engine_tpu.models.gpt_dolomite import GPTDolomiteForCausalLM

from ..test_commons import assert_allclose, get_dense_test_config, get_dummy_inputs


def _cl_config(sharing_pattern=None, **kwargs) -> GPTCrossLayerConfig:
    return GPTCrossLayerConfig(
        vocab_size=2048,
        n_positions=512,
        n_embd=32,
        n_layer=4,
        n_head=4,
        num_key_value_heads=2,
        position_embedding_type=kwargs.pop("position_embedding_type", "rope"),
        activation_function="swiglu",
        normalization_function="rmsnorm",
        add_bias=kwargs.pop("add_bias", False),
        sharing_pattern=sharing_pattern,
        resid_pdrop=0.0,
        embd_pdrop=0.0,
        attn_pdrop=0.0,
        bos_token_id=0,
        eos_token_id=1,
        pad_token_id=2,
        **kwargs,
    )


def test_group_layout():
    assert group_layout([0, 1, 2, 3]) == [1, 1, 1, 1]
    assert group_layout([0, 0, 2, 2]) == [2, 2]
    assert group_layout([0, 0, 0, 3]) == [3, 1]


def test_sharing_pattern_validation():
    _cl_config(sharing_pattern=[0, 2, 2, 2])  # valid: parents 0 and 2 both self-reference
    with pytest.raises(AssertionError):
        _cl_config(sharing_pattern=[2, 2, 0, 0])  # decreasing
    with pytest.raises(AssertionError):
        _cl_config(sharing_pattern=[0, 0, 1, 1])  # parent 1 not self-referencing


@pytest.mark.parametrize("sharing_pattern", [[0, 0, 2, 2], [0, 0, 0, 0], None])
def test_forward_and_loss(sharing_pattern):
    config = _cl_config(sharing_pattern=sharing_pattern)
    model = GPTCrossLayerForCausalLM(config=config)
    ids, mask = get_dummy_inputs(config)
    params = model.init(jax.random.PRNGKey(0), ids)
    out = model.apply(params, ids, attention_mask=mask, compute_loss=True)
    assert out.logits.shape == (*ids.shape, config.vocab_size)
    assert np.isfinite(float(out.loss))
    # parameter sharing: only group parents own a kv projection
    n_groups = len(group_layout(config.sharing_pattern))
    kv_projs = [k for k in params["params"]["transformer"] if k.startswith("h_")]
    assert len(kv_projs) == n_groups


def test_conversion_identity_pattern_matches_original():
    """With sharing_pattern = identity the converted model reproduces GPTDolomite exactly
    (reference tests the same via convert_gpt_dolomite_to_gpt_crosslayer)."""
    base_config = get_dense_test_config(
        "gqa", "rope", activation_function="swiglu", normalization_function="rmsnorm",
        add_bias=False,
    )
    base = GPTDolomiteForCausalLM(config=base_config)
    ids, mask = get_dummy_inputs(base_config)
    base_params = base.init(jax.random.PRNGKey(0), ids)
    base_out = base.apply(base_params, ids, attention_mask=mask)

    cl_config, cl_params = convert_gpt_dolomite_to_gpt_crosslayer(
        base_config, base_params["params"]
    )
    cl_model = GPTCrossLayerForCausalLM(config=cl_config)
    cl_out = cl_model.apply({"params": cl_params}, ids, attention_mask=mask)

    valid = np.asarray(mask).astype(bool)
    assert_allclose(
        np.asarray(cl_out.logits)[valid], np.asarray(base_out.logits)[valid],
        atol=2e-5, rtol=2e-5,
    )


def test_conversion_shared_pattern_shapes():
    base_config = get_dense_test_config(
        "gqa", "rope", activation_function="swiglu", normalization_function="rmsnorm",
        add_bias=True,
    )
    base = GPTDolomiteForCausalLM(config=base_config)
    ids, _ = get_dummy_inputs(base_config)
    base_params = base.init(jax.random.PRNGKey(0), ids)

    cl_config, cl_params = convert_gpt_dolomite_to_gpt_crosslayer(
        base_config, base_params["params"], sharing_pattern=[0, 0, 2, 2]
    )
    cl_model = GPTCrossLayerForCausalLM(config=cl_config)
    out = cl_model.apply({"params": cl_params}, ids)
    assert np.all(np.isfinite(np.asarray(out.logits)))

    # converted params must be loadable 1:1 into a fresh init's structure
    fresh = cl_model.init(jax.random.PRNGKey(1), ids)["params"]
    import flax.linen as nn

    fresh_paths = set(jax.tree_util.keystr(p) for p, _ in
                      jax.tree_util.tree_flatten_with_path(nn.unbox(fresh))[0])
    conv_paths = set(jax.tree_util.keystr(p) for p, _ in
                     jax.tree_util.tree_flatten_with_path(cl_params)[0])
    assert fresh_paths == conv_paths


def test_conversion_parent_not_at_group_start():
    """Pattern [0, 2, 2, 2] is valid (parent 2 self-references mid-group); the converter must
    still emit a kv_proj for that group (from the parent layer's c_attn)."""
    base_config = get_dense_test_config(
        "gqa", "rope", activation_function="swiglu", normalization_function="rmsnorm",
        add_bias=False,
    )
    base = GPTDolomiteForCausalLM(config=base_config)
    ids, _ = get_dummy_inputs(base_config)
    base_params = base.init(jax.random.PRNGKey(0), ids)

    cl_config, cl_params = convert_gpt_dolomite_to_gpt_crosslayer(
        base_config, base_params["params"], sharing_pattern=[0, 2, 2, 2]
    )
    cl_model = GPTCrossLayerForCausalLM(config=cl_config)
    out = cl_model.apply({"params": cl_params}, ids)
    assert np.all(np.isfinite(np.asarray(out.logits)))


def test_kv_cache_decode_matches_full_forward():
    config = _cl_config(sharing_pattern=[0, 0, 2, 2])
    model = GPTCrossLayerForCausalLM(config=config)
    rs = np.random.RandomState(1)
    ids = jnp.asarray(rs.randint(0, config.vocab_size, (2, 12)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), ids)

    full = model.apply(params, ids)

    caches = model.init_kv_caches(2, 12)
    assert len(caches) == 2  # one per group, not per layer
    prefill = model.apply(params, ids[:, :8], kv_caches=caches, cache_index=jnp.zeros((), jnp.int32))
    logits = [prefill.logits]
    caches = prefill.kv_caches
    for t in range(8, 12):
        step = model.apply(
            params, ids[:, t : t + 1], kv_caches=caches, cache_index=jnp.asarray(t, jnp.int32)
        )
        caches = step.kv_caches
        logits.append(step.logits)
    assert_allclose(jnp.concatenate(logits, axis=1), full.logits, atol=3e-4, rtol=3e-4)


def test_joint_residual_stream():
    config = _cl_config(sharing_pattern=[0, 0, 0, 0], joint_residual_stream=True)
    model = GPTCrossLayerForCausalLM(config=config)
    ids, _ = get_dummy_inputs(config)
    params = model.init(jax.random.PRNGKey(0), ids)
    out = model.apply(params, ids)
    assert np.all(np.isfinite(np.asarray(out.logits)))
