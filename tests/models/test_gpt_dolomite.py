"""GPTDolomite numerical tests.

Parity: reference `tests/hf_models/single_gpu/gpt_dolomite_test.py` — attention-implementation
equivalence matrix over head-type x position-embedding, KV-cache generation consistency,
padding-free (segment-ids) vs batched equivalence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dolomite_engine_tpu.enums import AttentionImplementation
from dolomite_engine_tpu.models.gpt_dolomite import GPTDolomiteForCausalLM

from ..test_commons import assert_allclose, get_dense_test_config, get_dummy_inputs

HEAD_TYPES = ["mha", "mqa", "gqa"]
POS_EMBS = ["learned_absolute", "alibi", "rope", "nope"]


def _build(config, attention_implementation=AttentionImplementation.sdpa, **kwargs):
    model = GPTDolomiteForCausalLM(
        config=config, attention_implementation=attention_implementation, **kwargs
    )
    ids, mask = get_dummy_inputs(config)
    params = model.init(jax.random.PRNGKey(0), ids)
    return model, params, ids, mask


@pytest.mark.parametrize("head_type", HEAD_TYPES)
@pytest.mark.parametrize("pos_emb", POS_EMBS)
def test_eager_sdpa_equivalence(head_type, pos_emb):
    config = get_dense_test_config(head_type, pos_emb)
    model, params, ids, mask = _build(config)

    out_sdpa = model.apply(params, ids, attention_mask=mask)
    model_eager = GPTDolomiteForCausalLM(
        config=config, attention_implementation=AttentionImplementation.eager
    )
    out_eager = model_eager.apply(params, ids, attention_mask=mask)

    valid = np.asarray(mask).astype(bool)
    assert_allclose(
        np.asarray(out_sdpa.logits)[valid],
        np.asarray(out_eager.logits)[valid],
        atol=2e-4,
        rtol=2e-4,
    )


@pytest.mark.parametrize("head_type", HEAD_TYPES)
def test_loss_matches_manual_shift(head_type):
    config = get_dense_test_config(head_type, "rope", normalization_function="rmsnorm")
    model, params, ids, _ = _build(config)
    out = model.apply(params, ids, compute_loss=True)

    logits = np.asarray(out.logits, np.float32)
    logprobs = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
    manual = -np.mean(
        [
            np.asarray(logprobs)[b, t, ids[b, t + 1]]
            for b in range(ids.shape[0])
            for t in range(ids.shape[1] - 1)
        ]
    )
    assert_allclose(out.loss, manual, atol=1e-5, rtol=1e-5)


def test_packed_segment_equivalence():
    """Packed two-document row with segment ids == two separate rows (padding-free parity)."""
    config = get_dense_test_config("mqa", "rope")
    model = GPTDolomiteForCausalLM(config=config)

    rs = np.random.RandomState(0)
    doc_a = rs.randint(0, config.vocab_size, (1, 8)).astype(np.int32)
    doc_b = rs.randint(0, config.vocab_size, (1, 8)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(doc_a))

    packed_ids = jnp.concatenate([jnp.asarray(doc_a), jnp.asarray(doc_b)], axis=1)
    segment_ids = jnp.asarray([[1] * 8 + [2] * 8])
    position_ids = jnp.asarray([list(range(8)) + list(range(8))])
    out_packed = model.apply(
        params, packed_ids, position_ids=position_ids, segment_ids=segment_ids
    )

    out_a = model.apply(params, jnp.asarray(doc_a))
    out_b = model.apply(params, jnp.asarray(doc_b))

    assert_allclose(out_packed.logits[:, :8], out_a.logits, atol=2e-4, rtol=2e-4)
    assert_allclose(out_packed.logits[:, 8:], out_b.logits, atol=2e-4, rtol=2e-4)


def test_kv_cache_decode_matches_full_forward():
    config = get_dense_test_config("gqa", "rope")
    model = GPTDolomiteForCausalLM(config=config)
    rs = np.random.RandomState(1)
    ids = jnp.asarray(rs.randint(0, config.vocab_size, (2, 12)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), ids)

    full = model.apply(params, ids)

    # prefill 8, then decode 4 one by one
    caches = model.init_kv_caches(2, 12)
    prefill = model.apply(
        params, ids[:, :8], kv_caches=caches, cache_index=jnp.zeros((), jnp.int32)
    )
    logits = [prefill.logits]
    caches = prefill.kv_caches
    for t in range(8, 12):
        step = model.apply(
            params,
            ids[:, t : t + 1],
            kv_caches=caches,
            cache_index=jnp.asarray(t, jnp.int32),
        )
        caches = step.kv_caches
        logits.append(step.logits)

    decoded = jnp.concatenate(logits, axis=1)
    assert_allclose(decoded, full.logits, atol=3e-4, rtol=3e-4)


def test_mup_multipliers_applied():
    config = get_dense_test_config(
        "mqa", "rope", m_emb=2.0, m_width=4.0, m_residual=0.5, init_method="mup"
    )
    model, params, ids, _ = _build(config)
    out = model.apply(params, ids)
    assert out.logits.shape == (*ids.shape, config.vocab_size)
    assert bool(jnp.all(jnp.isfinite(out.logits)))


def test_tied_and_untied_lm_head():
    tied = get_dense_test_config("mqa", "rope")
    untied = get_dense_test_config("mqa", "rope", tie_word_embeddings=False)
    m1, p1, ids, _ = _build(tied)
    m2, p2, _, _ = _build(untied)
    assert "lm_head" not in p1["params"]
    assert "lm_head" in p2["params"]
    assert m2.apply(p2, ids).logits.shape == m1.apply(p1, ids).logits.shape


def test_checkpoint_policy_remat_is_numerics_identical():
    """gradient_checkpointing_args.checkpoint_policy maps to jax.checkpoint_policies and
    changes rematerialization only: loss AND grads are bit-identical to no-remat; unknown
    names fail loudly with the valid list."""
    config = get_dense_test_config("mqa", "rope")
    ids, _ = get_dummy_inputs(config, padded=False)

    results = {}
    for name, kwargs in [
        ("none", {}),
        ("block", dict(checkpoint_every=1)),
        ("dots", dict(checkpoint_every=1, checkpoint_policy="dots_saveable")),
        # the named-policy vocabulary (gradient_checkpointing_args.policy): every
        # policy must be a pure remat-schedule change — same loss, ulp-same grads
        ("full", dict(checkpoint_every=1, checkpoint_policy="full")),
        ("save_dots", dict(checkpoint_every=1, checkpoint_policy="save_dots")),
        (
            "save_attention_out",
            dict(checkpoint_every=1, checkpoint_policy="save_attention_out"),
        ),
        # offload_dots falls back to save_dots off-TPU (no pinned_host) with a warning;
        # numerics are policy-independent either way
        ("offload_dots", dict(checkpoint_every=1, checkpoint_policy="offload_dots")),
        ("every_2_save_dots", dict(checkpoint_every=2, checkpoint_policy="save_dots")),
    ]:
        model = GPTDolomiteForCausalLM(config=config, **kwargs)
        params = model.init(jax.random.PRNGKey(0), ids)

        def loss_fn(p):
            return model.apply(p, ids, labels=ids, compute_loss=True).loss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        flat = jax.flatten_util.ravel_pytree(grads)[0]
        results[name] = (float(loss), np.asarray(flat))

    for name in results:
        if name == "none":
            continue
        assert results[name][0] == results["none"][0], name
        # grads: this container's CPU XLA reassociates one fusion differently under remat,
        # costing 1 ulp on ~30% of elements (verified identical on unmodified seed code);
        # assert to float32-ulp tolerance instead of bitwise so the property under test —
        # remat changes rematerialization only, not math — still binds tightly
        np.testing.assert_allclose(
            results[name][1], results["none"][1], rtol=0, atol=1.2e-7, err_msg=name
        )

    with pytest.raises(ValueError, match="unknown checkpoint_policy"):
        GPTDolomiteForCausalLM(
            config=config, checkpoint_every=1, checkpoint_policy="nope"
        ).init(jax.random.PRNGKey(0), ids)
