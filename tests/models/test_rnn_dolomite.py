"""RNNDolomite / DeltaNet tests.

The load-bearing check: the chunked WY-form delta rule must match the step-by-step
recurrence exactly (the reference trusts external fla Triton kernels for this; here both
implementations are in-repo and cross-checked).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dolomite_engine_tpu.models.config import RNNDolomiteConfig
from dolomite_engine_tpu.models.rnn_dolomite import RNNDolomiteForCausalLM
from dolomite_engine_tpu.ops.deltanet import (
    delta_rule_chunked,
    delta_rule_recurrent,
    l2_norm,
    short_convolution,
)

from ..test_commons import assert_allclose


def _qkvb(batch=2, heads=2, length=128, dk=8, dv=8, seed=0):
    rs = np.random.RandomState(seed)
    q = l2_norm(jnp.asarray(rs.randn(batch, heads, length, dk).astype(np.float32)))
    k = l2_norm(jnp.asarray(rs.randn(batch, heads, length, dk).astype(np.float32)))
    v = jnp.asarray(rs.randn(batch, heads, length, dv).astype(np.float32))
    beta = jax.nn.sigmoid(jnp.asarray(rs.randn(batch, heads, length).astype(np.float32)))
    return q, k, v, beta


@pytest.mark.parametrize("chunk_size", [16, 32, 64])
def test_chunked_matches_recurrent(chunk_size):
    q, k, v, beta = _qkvb()
    o_rec, s_rec = delta_rule_recurrent(q, k, v, beta)
    o_chk, s_chk = delta_rule_chunked(q, k, v, beta, chunk_size)
    assert_allclose(o_chk, o_rec, atol=1e-4, rtol=1e-4)
    assert_allclose(s_chk, s_rec, atol=1e-4, rtol=1e-4)


def test_chunked_with_initial_state():
    q, k, v, beta = _qkvb(length=64)
    q2, k2, v2, beta2 = _qkvb(length=64, seed=1)
    # full pass == two passes threading the state
    o_full, s_full = delta_rule_recurrent(
        jnp.concatenate([q, q2], 2), jnp.concatenate([k, k2], 2),
        jnp.concatenate([v, v2], 2), jnp.concatenate([beta, beta2], 2),
    )
    _, s1 = delta_rule_chunked(q, k, v, beta, 32)
    o2, s2 = delta_rule_chunked(q2, k2, v2, beta2, 32, initial_state=s1)
    assert_allclose(o2, o_full[:, :, 64:], atol=1e-4, rtol=1e-4)
    assert_allclose(s2, s_full, atol=1e-4, rtol=1e-4)


def test_zero_beta_is_noop_on_state():
    q, k, v, beta = _qkvb(length=16)
    o1, s1 = delta_rule_recurrent(q, k, v, beta)
    # append positions with beta == 0: state unchanged
    pad = 4
    qp = jnp.concatenate([q, q[:, :, :pad]], 2)
    kp = jnp.concatenate([k, k[:, :, :pad]], 2)
    vp = jnp.concatenate([v, v[:, :, :pad]], 2)
    bp = jnp.concatenate([beta, jnp.zeros_like(beta[:, :, :pad])], 2)
    _, s2 = delta_rule_recurrent(qp, kp, vp, bp)
    assert_allclose(s2, s1, atol=1e-6, rtol=1e-6)


def test_short_convolution_causal_and_state():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(2, 10, 6).astype(np.float32))
    w = jnp.asarray(rs.randn(6, 4).astype(np.float32) * 0.3)

    y, state = short_convolution(x, w, activation=None)
    # causality: y[t] depends only on x[<=t]
    manual = np.zeros((2, 10, 6), np.float32)
    xn = np.asarray(x)
    for t in range(10):
        for i in range(4):
            src = t - 3 + i
            if src >= 0:
                manual[:, t] += np.asarray(w)[:, i] * xn[:, src]
    assert_allclose(y, manual, atol=1e-5, rtol=1e-5)

    # streaming: feeding one token with the saved state == full-sequence result
    y_full, _ = short_convolution(
        jnp.concatenate([x, x[:, :1]], 1), w, activation=None
    )
    y_step, _ = short_convolution(x[:, :1], w, activation=None, conv_state=state)
    assert_allclose(y_step, y_full[:, -1:], atol=1e-5, rtol=1e-5)


def _config(pattern="daad") -> RNNDolomiteConfig:
    return RNNDolomiteConfig(
        vocab_size=2048,
        n_positions=512,
        n_embd=32,
        n_layer=len(pattern),
        n_head=4,
        attention_head_type="mha",
        num_key_value_heads=4,
        position_embedding_type="nope",
        activation_function="swiglu",
        normalization_function="rmsnorm",
        add_bias=False,
        attention_pattern=pattern,
        resid_pdrop=0.0,
        embd_pdrop=0.0,
        attn_pdrop=0.0,
        bos_token_id=0,
        eos_token_id=1,
        pad_token_id=2,
    )


@pytest.mark.parametrize("pattern", ["dd", "da", "ad"])
def test_forward_and_loss(pattern):
    config = _config(pattern)
    model = RNNDolomiteForCausalLM(config=config)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, config.vocab_size, (2, 16)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), ids)
    out = model.apply(params, ids, compute_loss=True)
    assert out.logits.shape == (*ids.shape, config.vocab_size)
    assert np.isfinite(float(out.loss))
    # deltanet layers have conv + delta params, attention layers have fused c_attn
    h0 = params["params"]["transformer"]["h_0"]["attn"]
    if pattern[0] == "d":
        assert "q_conv1d" in h0 and "b_proj" in h0
    else:
        assert "c_attn" in h0


def test_decode_matches_full_forward():
    """Streaming decode through conv+recurrent state == full forward (hybrid stack)."""
    config = _config("da")
    model = RNNDolomiteForCausalLM(config=config)
    rs = np.random.RandomState(1)
    ids = jnp.asarray(rs.randint(0, config.vocab_size, (2, 12)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), ids)

    full = model.apply(params, ids)

    caches = model.init_kv_caches(2, 12)
    assert "recurrent" in caches[0] and "k" in caches[1]
    prefill = model.apply(params, ids[:, :8], kv_caches=caches, cache_index=jnp.zeros((), jnp.int32))
    logits = [prefill.logits]
    caches = prefill.kv_caches
    for t in range(8, 12):
        step = model.apply(
            params, ids[:, t : t + 1], kv_caches=caches, cache_index=jnp.asarray(t, jnp.int32)
        )
        caches = step.kv_caches
        logits.append(step.logits)
    assert_allclose(jnp.concatenate(logits, axis=1), full.logits, atol=5e-4, rtol=5e-4)


def test_chunked_path_in_model():
    """Sequence length that is a chunk multiple routes through delta_rule_chunked."""
    config = _config("dd")
    model = RNNDolomiteForCausalLM(config=config)
    rs = np.random.RandomState(2)
    ids = jnp.asarray(rs.randint(0, config.vocab_size, (1, 128)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), ids[:, :16])
    out = model.apply(params, ids)
    assert np.all(np.isfinite(np.asarray(out.logits)))
