"""DenseMoE tests: wide-MLP soft routing, MoA head gating, inference-time sparsification.

The reference has no dense_moe unit tests; coverage here follows the same matrix style as the
other families plus the paper's key property: dense training == sparse inference when the
router mass is concentrated.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dolomite_engine_tpu.models.config import DenseMoEConfig
from dolomite_engine_tpu.models.dense_moe import DenseMoEForCausalLM, mask_probability

from ..test_commons import assert_allclose, get_dummy_inputs


def _config(**kwargs) -> DenseMoEConfig:
    return DenseMoEConfig(
        vocab_size=2048,
        n_positions=512,
        n_embd=32,
        n_layer=2,
        n_head=4,
        num_experts=kwargs.pop("num_experts", 2),
        position_embedding_type=kwargs.pop("position_embedding_type", "rope"),
        activation_function=kwargs.pop("activation_function", "swiglu"),
        normalization_function="rmsnorm",
        add_bias=False,
        resid_pdrop=0.0,
        embd_pdrop=0.0,
        attn_pdrop=0.0,
        bos_token_id=0,
        eos_token_id=1,
        pad_token_id=2,
        **kwargs,
    )


def test_head_divisibility_enforced():
    with pytest.raises(AssertionError):
        _config(num_experts=3)  # 4 heads % 3 != 0


def test_mask_probability():
    p = jnp.asarray([[0.5, 0.3, 0.15, 0.05]])
    np.testing.assert_array_equal(np.asarray(mask_probability(p, None)), np.asarray(p))
    thresholded = np.asarray(mask_probability(p, {"threshold": 0.2}))
    np.testing.assert_allclose(thresholded, [[0.5, 0.3, 0.0, 0.0]])
    topk = np.asarray(mask_probability(p, {"top_k": 1}))
    np.testing.assert_allclose(topk, [[0.5, 0.0, 0.0, 0.0]])
    with pytest.raises(ValueError):
        mask_probability(p, {})


@pytest.mark.parametrize("pos_emb", ["rope", "learned_absolute"])
def test_forward_and_loss(pos_emb):
    config = _config(position_embedding_type=pos_emb)
    model = DenseMoEForCausalLM(config=config)
    ids, mask = get_dummy_inputs(config)
    params = model.init(jax.random.PRNGKey(0), ids)
    out = model.apply(params, ids, attention_mask=mask, compute_loss=True)
    assert out.logits.shape == (*ids.shape, config.vocab_size)
    assert np.isfinite(float(out.loss))
    # wide MLP: c_fc spans num_experts * n_inner (x2 for GLU)
    c_fc = params["params"]["transformer"]["h_0"]["mlp"]["c_fc"]["kernel"]
    assert c_fc.value.shape[-1] == 2 * config.num_experts * config.n_inner


def test_inference_masking_changes_output():
    config = _config()
    dense = DenseMoEForCausalLM(config=config)
    sparse = DenseMoEForCausalLM(config=config, inference_method={"top_k": 1})
    ids, _ = get_dummy_inputs(config, padded=False)
    params = dense.init(jax.random.PRNGKey(0), ids)
    out_dense = dense.apply(params, ids)
    out_sparse = sparse.apply(params, ids)
    assert not np.allclose(np.asarray(out_dense.logits), np.asarray(out_sparse.logits))
    # threshold 0 keeps everything -> identical to dense
    keep_all = DenseMoEForCausalLM(config=config, inference_method={"threshold": 0.0})
    out_keep = keep_all.apply(params, ids)
    assert_allclose(out_keep.logits, out_dense.logits, atol=1e-6, rtol=1e-6)


def test_kv_cache_decode_matches_full_forward():
    config = _config()
    model = DenseMoEForCausalLM(config=config)
    rs = np.random.RandomState(1)
    ids = jnp.asarray(rs.randint(0, config.vocab_size, (2, 10)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), ids)

    full = model.apply(params, ids)
    caches = model.init_kv_caches(2, 10)
    # one KV head per expert
    assert caches[0]["k"].shape[2] == config.num_experts

    prefill = model.apply(params, ids[:, :6], kv_caches=caches, cache_index=jnp.zeros((), jnp.int32))
    logits = [prefill.logits]
    caches = prefill.kv_caches
    for t in range(6, 10):
        step = model.apply(
            params, ids[:, t : t + 1], kv_caches=caches, cache_index=jnp.asarray(t, jnp.int32)
        )
        caches = step.kv_caches
        logits.append(step.logits)
    assert_allclose(jnp.concatenate(logits, axis=1), full.logits, atol=3e-4, rtol=3e-4)
