"""scan_layers: nn.scan over one transformer block (TPU compile-time feature, no reference
counterpart — torch.compile re-traces every block; here XLA compiles a single layer).

Correctness bar: bit-identical math to the unrolled model on the same weights, working
ZeRO-3 sharded training on the virtual mesh, and an export path equal to the unrolled
model's safetensors layout."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dolomite_engine_tpu.enums import AttentionImplementation, Mode
from dolomite_engine_tpu.models import config_from_dict
from dolomite_engine_tpu.models.gpt_dolomite import (
    GPTDolomiteForCausalLM,
    stack_block_params,
    unstack_block_params,
)


def _config(n_layer=3):
    return config_from_dict(
        dict(
            model_type="gpt_dolomite",
            vocab_size=256,
            n_positions=64,
            n_embd=32,
            n_layer=n_layer,
            n_head=4,
            num_key_value_heads=2,
            attention_head_type="gqa",
            position_embedding_type="rope",
            activation_function="swiglu",
            normalization_function="rmsnorm",
            add_bias=False,
            resid_pdrop=0.0,
            embd_pdrop=0.0,
            attn_pdrop=0.0,
            bos_token_id=0,
            eos_token_id=1,
            pad_token_id=2,
        )
    )


def test_scan_matches_unrolled_on_same_weights():
    config = _config()
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 256, size=(2, 32)), jnp.int32)

    unrolled = GPTDolomiteForCausalLM(config=config)
    params = unrolled.init(jax.random.PRNGKey(0), ids)["params"]
    ref = unrolled.apply({"params": params}, ids).logits

    scanned = GPTDolomiteForCausalLM(config=config, scan_layers=True)
    stacked = stack_block_params(params, config.n_layer)
    out = scanned.apply({"params": stacked}, ids).logits
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)

    # round-trip back to the unrolled layout (helpers operate on unboxed trees)
    from flax import linen as nn

    back = unstack_block_params(stacked, config.n_layer)
    chex_equal = jax.tree.map(
        lambda a, b: np.array_equal(np.asarray(a), np.asarray(b)), nn.unbox(params), back
    )
    assert all(jax.tree.leaves(chex_equal))


def test_scan_init_shapes_are_stacked():
    config = _config()
    ids = jnp.zeros((1, 16), jnp.int32)
    model = GPTDolomiteForCausalLM(config=config, scan_layers=True)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    t = params["transformer"]
    assert "h_scan" in t and "h_0" not in t
    kernel = t["h_scan"]["attn"]["c_attn"]["kernel"]
    kernel = kernel.unbox() if hasattr(kernel, "unbox") else kernel
    assert kernel.shape[0] == config.n_layer
    # per-layer init rngs are split: layers must not be identical copies
    assert not np.allclose(np.asarray(kernel[0]), np.asarray(kernel[1]))


def test_scan_remat_matches_no_remat():
    config = _config()
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 256, size=(2, 32)), jnp.int32)
    model = GPTDolomiteForCausalLM(config=config, scan_layers=True)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    ref = model.apply({"params": params}, ids).logits
    remat = GPTDolomiteForCausalLM(config=config, scan_layers=True, checkpoint_every=1)
    out = remat.apply({"params": params}, ids).logits
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_scan_grouped_every_k_remat_matches_unrolled():
    """scan_layers v2: checkpoint_every=k that divides n_layer scans over k-block GROUPS
    (BlockGroup) — every-k remat composes with scan, bit-equal to the unrolled model, with
    gradients matching the every-block-remat scan."""
    config = _config(n_layer=4)
    ids = jnp.asarray(np.random.RandomState(2).randint(0, 256, size=(2, 32)), jnp.int32)

    unrolled = GPTDolomiteForCausalLM(config=config)
    params = unrolled.init(jax.random.PRNGKey(0), ids)["params"]
    ref = unrolled.apply({"params": params}, ids).logits

    grouped = GPTDolomiteForCausalLM(config=config, scan_layers=True, checkpoint_every=2)
    gparams = stack_block_params(params, config.n_layer, group_size=2)
    # grouped layout: h_scan.b{j} stacked over the 2 groups
    assert set(gparams["transformer"]["h_scan"].keys()) == {"b0", "b1"}
    out = grouped.apply({"params": gparams}, ids).logits
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)

    # gradients flow and match the ungrouped scan's gradients on the same weights
    def loss_g(p):
        return grouped.apply(
            {"params": p}, ids, labels=jnp.where(ids > 0, ids, -100), compute_loss=True
        ).loss

    plain = GPTDolomiteForCausalLM(config=config, scan_layers=True, checkpoint_every=1)
    pparams = stack_block_params(params, config.n_layer)

    def loss_p(p):
        return plain.apply(
            {"params": p}, ids, labels=jnp.where(ids > 0, ids, -100), compute_loss=True
        ).loss

    g_grouped = jax.grad(loss_g)(gparams)
    g_plain = jax.grad(loss_p)(pparams)
    # compare a shared non-block leaf exactly and one block leaf through the layout map
    np.testing.assert_allclose(
        np.asarray(g_grouped["transformer"]["wte"]["embedding"]),
        np.asarray(g_plain["transformer"]["wte"]["embedding"]),
        atol=1e-5,
        rtol=1e-5,
    )
    # b0[g] is layer 2g, i.e. stacked plain rows (0, 2); b1[g] is layers (1, 3)
    plain_blocks = g_plain["transformer"]["h_scan"]
    grouped_blocks = g_grouped["transformer"]["h_scan"]
    for j, rows in (("b0", (0, 2)), ("b1", (1, 3))):
        np.testing.assert_allclose(
            np.asarray(grouped_blocks[j]["attn"]["c_attn"]["kernel"]),
            np.asarray(plain_blocks["attn"]["c_attn"]["kernel"])[list(rows)],
            atol=1e-5,
            rtol=1e-5,
        )

    # unstack is layout-aware and returns the exact unrolled tree
    from flax import linen as nn

    restored = unstack_block_params(gparams, config.n_layer)
    unboxed = nn.unbox(params)
    for path, leaf in jax.tree_util.tree_leaves_with_path(restored):
        ref_leaf = unboxed
        for k in path:
            ref_leaf = ref_leaf[k.key]
        np.testing.assert_array_equal(np.asarray(leaf), np.asarray(ref_leaf))


def test_scan_export_matches_unrolled_layout():
    from dolomite_engine_tpu.hf_interop.weights import params_to_state_dict

    config = _config()
    ids = jnp.zeros((1, 16), jnp.int32)
    unrolled = GPTDolomiteForCausalLM(config=config)
    params = unrolled.init(jax.random.PRNGKey(0), ids)["params"]
    sd_ref = params_to_state_dict(config, params)
    sd_scan = params_to_state_dict(config, stack_block_params(params, config.n_layer))
    assert sd_ref.keys() == sd_scan.keys()
    for k in sd_ref:
        np.testing.assert_array_equal(sd_ref[k], sd_scan[k])


def test_scan_sharded_train_step(eight_devices):
    """ZeRO-3 train step with scanned blocks on the 8-device mesh ('layers' axis rule)."""
    from dolomite_engine_tpu.distributed import create_sharded_train_state
    from dolomite_engine_tpu.enums import LRDecaySchedule
    from dolomite_engine_tpu.model_wrapper.pretraining import ModelWrapperForPretraining
    from dolomite_engine_tpu.optimization import get_optimizer, get_scheduler
    from dolomite_engine_tpu.parallel.mesh import MeshManager, named_sharding
    from dolomite_engine_tpu.train_utils import make_train_step

    MeshManager()
    mesh = MeshManager.get_mesh()
    try:
        seq = 32
        wrapper = ModelWrapperForPretraining(
            mode=Mode.training,
            pretrained_config=dict(_config(n_layer=2).to_dict()),
            dtype="fp32",
            sequence_length=seq,
            zero_stage=3,
            model_kwargs={"scan_layers": True},
        )
        sched = get_scheduler(2, 0, None, 10, LRDecaySchedule.cosine, 0.1, base_lr=1e-3)
        opt = get_optimizer(
            "TorchAdamW", {"weight_decay": 0.1, "betas": (0.9, 0.95), "eps": 1e-10}, sched
        )
        state, _ = create_sharded_train_state(wrapper, opt, mesh, jax.random.PRNGKey(0))

        def loss_fn(params, micro, rng):
            return wrapper.loss(params, micro["text"], train=True)

        step = jax.jit(make_train_step(loss_fn, opt, gradient_accumulation_steps=2),
                       donate_argnums=0)
        tokens = np.random.RandomState(0).randint(0, 256, size=(2, 8, seq + 1)).astype(np.int32)
        with mesh:
            batch = {
                "text": jax.device_put(jnp.asarray(tokens), named_sharding(None, ("dp", "fsdp")))
            }
            losses = []
            for i in range(3):
                state, metrics = step(state, batch, jax.random.PRNGKey(i))
                losses.append(float(metrics["loss"]))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0], losses
    finally:
        MeshManager.destroy()


def test_scan_rejects_moe_and_generation():
    from dolomite_engine_tpu.models import MoEDolomiteForCausalLM
    from dolomite_engine_tpu.models.config import MoEConfig

    moe_config = MoEConfig(
        vocab_size=128, n_positions=32, n_embd=32, n_layer=2, n_head=4,
        attention_head_type="mha", num_experts=2, num_experts_per_tok=1,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    model = MoEDolomiteForCausalLM(config=moe_config, scan_layers=True)
    with pytest.raises(AssertionError, match="homogeneous"):
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))

    # kv-cache decode path must refuse scanned params rather than produce garbage
    config = _config(n_layer=2)
    scanned = GPTDolomiteForCausalLM(config=config, scan_layers=True)
    ids = jnp.zeros((1, 8), jnp.int32)
    params = scanned.init(jax.random.PRNGKey(0), ids)["params"]
    caches = scanned.init_kv_caches(1, 16)
    with pytest.raises(AssertionError, match="training-path"):
        scanned.apply({"params": params}, ids, kv_caches=caches, cache_index=0)


def test_scan_wrapper_guards_and_load_roundtrip(tmp_path):
    """Wrapper refuses scan_layers for non-gpt_dolomite families and for generate();
    load_pretrained_params stacks an unrolled checkpoint into the scanned layout."""
    from dolomite_engine_tpu.model_wrapper.base import ModelWrapper
    from dolomite_engine_tpu.model_wrapper.pretraining import ModelWrapperForFinetuning

    with pytest.raises(ValueError, match="scan_layers supports gpt_dolomite"):
        ModelWrapper(
            mode=Mode.training,
            pretrained_config=dict(
                model_type="moe_dolomite", vocab_size=128, n_positions=32, n_embd=32,
                n_layer=2, n_head=4, attention_head_type="mha", num_experts=2,
                num_experts_per_tok=1,
            ),
            model_kwargs={"scan_layers": True},
        )

    config = _config(n_layer=2)
    wrapper = ModelWrapperForFinetuning(
        mode=Mode.training,
        pretrained_config=dict(config.to_dict()),
        model_kwargs={"scan_layers": True},
    )
    with pytest.raises(AssertionError, match="unrolled"):
        wrapper.generate(None, {"input_ids": [[1]], "attention_mask": [[1]]}, {})

    # save an unrolled checkpoint, load it into the scanned wrapper, logits must match
    from dolomite_engine_tpu.parallel.mesh import MeshManager

    unrolled = GPTDolomiteForCausalLM(config=config)
    ids = jnp.asarray(np.random.RandomState(2).randint(0, 256, size=(1, 16)), jnp.int32)
    params = unrolled.init(jax.random.PRNGKey(0), ids)["params"]
    ref = unrolled.apply({"params": params}, ids).logits

    from dolomite_engine_tpu.hf_interop.weights import params_to_state_dict
    from dolomite_engine_tpu.utils.safetensors import SafeTensorsWeightsManager

    SafeTensorsWeightsManager.save_state_dict(params_to_state_dict(config, params), str(tmp_path))

    MeshManager()
    try:
        loaded = wrapper.load_pretrained_params(str(tmp_path), MeshManager.get_mesh())
        out = wrapper.model.apply({"params": loaded}, ids).logits
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)
    finally:
        MeshManager.destroy()


def test_scan_composes_with_ring_cp(eight_devices):
    """scan-of-shard_map: scanned blocks with ring context parallelism (sp=2) compile and
    match the unscanned ring model on the same weights."""
    from dolomite_engine_tpu.parallel.mesh import MeshManager

    config = _config(n_layer=2)
    ids = jnp.asarray(np.random.RandomState(3).randint(0, 256, size=(2, 32)), jnp.int32)

    MeshManager(sequence_parallel_size=2, data_parallel_sharding_world_size=4)
    mesh = MeshManager.get_mesh()
    try:
        with mesh:
            unrolled = GPTDolomiteForCausalLM(
                config=config, attention_implementation=AttentionImplementation.ring
            )
            params = unrolled.init(jax.random.PRNGKey(0), ids)["params"]
            ref = unrolled.apply({"params": params}, ids).logits

            scanned = GPTDolomiteForCausalLM(
                config=config,
                attention_implementation=AttentionImplementation.ring,
                scan_layers=True,
            )
            out = scanned.apply(
                {"params": stack_block_params(params, config.n_layer)}, ids
            ).logits
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)
    finally:
        MeshManager.destroy()


def _enc_dec_config(n_layer=3, n_encoder_layer=2):
    from dolomite_engine_tpu.models.config import EncDecDolomiteConfig

    return EncDecDolomiteConfig(
        vocab_size=128, n_positions=64, n_embd=32, n_layer=n_layer,
        n_encoder_layer=n_encoder_layer, n_head=4, num_key_value_heads=2,
        attention_head_type="gqa", position_embedding_type="rope",
        activation_function="swiglu", normalization_function="rmsnorm", add_bias=False,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        bos_token_id=0, eos_token_id=1, pad_token_id=2,
    )


def test_enc_dec_scan_matches_unrolled():
    """Seq2seq scan_layers: both stacks ride one scanned block each; bit-equal to the
    unrolled model on the same weights (incl. asymmetric stack depths), remat composes,
    and the converters round-trip."""
    from dolomite_engine_tpu.models.enc_dec_dolomite import (
        EncDecDolomiteForSeq2SeqLM,
        stack_enc_dec_params,
        unstack_enc_dec_params,
    )
    from flax import linen as nn

    config = _enc_dec_config()
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(3, 128, (2, 12)), jnp.int32)
    labels = jnp.asarray(rs.randint(3, 128, (2, 8)), jnp.int32)

    unrolled = EncDecDolomiteForSeq2SeqLM(config=config)
    params = unrolled.init(jax.random.PRNGKey(0), ids, labels=labels)["params"]
    ref = unrolled.apply({"params": params}, ids, labels=labels)

    scanned = EncDecDolomiteForSeq2SeqLM(config=config, scan_layers=True)
    sparams = stack_enc_dec_params(params, config.n_encoder_layer, config.n_layer)
    out = scanned.apply({"params": sparams}, ids, labels=labels)
    np.testing.assert_allclose(
        np.asarray(out.logits), np.asarray(ref.logits), atol=1e-5, rtol=1e-5
    )
    np.testing.assert_allclose(float(out.loss), float(ref.loss), atol=1e-6)

    # remat under scan is numerically identical
    remat = EncDecDolomiteForSeq2SeqLM(config=config, scan_layers=True, checkpoint_every=1)
    out_r = remat.apply({"params": sparams}, ids, labels=labels)
    np.testing.assert_allclose(
        np.asarray(out_r.logits), np.asarray(out.logits), atol=1e-6
    )

    # converters round-trip to the exact unrolled tree
    restored = unstack_enc_dec_params(sparams, config.n_encoder_layer, config.n_layer)
    unboxed = nn.unbox(params)
    for path, leaf in jax.tree_util.tree_leaves_with_path(restored):
        ref_leaf = unboxed
        for k in path:
            ref_leaf = ref_leaf[k.key]
        np.testing.assert_array_equal(np.asarray(leaf), np.asarray(ref_leaf))


def test_enc_dec_scan_export_and_sharded_step(eight_devices, tmp_path):
    """Scanned seq2seq exports the unrolled safetensors layout and trains ZeRO-3-sharded
    on the mesh through the wrapper (load path stacks on the fly)."""
    from dolomite_engine_tpu.distributed import create_sharded_train_state
    from dolomite_engine_tpu.enums import LRDecaySchedule
    from dolomite_engine_tpu.hf_interop.weights import params_to_state_dict
    from dolomite_engine_tpu.model_wrapper.pretraining import ModelWrapperForFinetuning
    from dolomite_engine_tpu.models.enc_dec_dolomite import (
        EncDecDolomiteForSeq2SeqLM,
        stack_enc_dec_params,
    )
    from dolomite_engine_tpu.optimization import get_optimizer, get_scheduler
    from dolomite_engine_tpu.parallel.mesh import MeshManager, named_sharding
    from dolomite_engine_tpu.train_utils import make_train_step

    config = _enc_dec_config()
    ids = jnp.zeros((1, 8), jnp.int32)
    unrolled = EncDecDolomiteForSeq2SeqLM(config=config)
    params = unrolled.init(jax.random.PRNGKey(0), ids, labels=ids)["params"]

    # export from the scanned layout == export from the unrolled layout
    sd_ref = params_to_state_dict(config, params)
    sd_scan = params_to_state_dict(
        config, stack_enc_dec_params(params, config.n_encoder_layer, config.n_layer)
    )
    assert sd_ref.keys() == sd_scan.keys()
    for k in sd_ref:
        np.testing.assert_array_equal(sd_ref[k], sd_scan[k])

    # sharded train step through the wrapper
    MeshManager.destroy()
    MeshManager(data_parallel_sharding_world_size=8)
    mesh = MeshManager.get_mesh()
    try:
        wrapper = ModelWrapperForFinetuning(
            mode=Mode.training,
            model_class="AutoModelForSeq2SeqLM",
            pretrained_config=config.to_dict(),
            dtype="fp32",
            model_kwargs={"scan_layers": True},
            zero_stage=3,
        )
        sched = get_scheduler(2, 0, None, 10, LRDecaySchedule.cosine, 0.1, base_lr=1e-3)
        opt = get_optimizer(
            "TorchAdamW", {"weight_decay": 0.1, "betas": (0.9, 0.95), "eps": 1e-10}, sched
        )
        state, _ = create_sharded_train_state(wrapper, opt, mesh, jax.random.PRNGKey(0))

        rs = np.random.RandomState(1)
        # leading axis = gradient-accumulation microbatches (train_utils.make_train_step)
        batch = {
            "input_ids": jnp.asarray(rs.randint(3, 128, (1, 8, 12)), jnp.int32),
            "attention_mask": jnp.ones((1, 8, 12), jnp.int32),
            "labels": jnp.asarray(rs.randint(3, 128, (1, 8, 8)), jnp.int32),
        }

        def loss_fn(p, micro, rng):
            return wrapper.loss(p, micro, train=True)

        step = make_train_step(loss_fn, opt, gradient_accumulation_steps=1)
        with mesh:
            sharded = {
                k: jax.device_put(v, named_sharding(None, ("dp", "fsdp")))
                for k, v in batch.items()
            }
            state, metrics = jax.jit(step, donate_argnums=0)(
                state, sharded, jax.random.PRNGKey(1)
            )
            loss = float(metrics["loss"])
        assert np.isfinite(loss)
    finally:
        MeshManager.destroy()
