#!/bin/bash
# End-to-end lifecycle on a laptop-class CPU in a few minutes: build a word-level
# tokenizer, tokenize a tiny corpus into the Megatron mmap format, pretrain a toy
# GPTDolomite on a virtual 8-device mesh (ZeRO-3 + packed segment ids), resume from the
# checkpoint, batch-generate, and export HF-layout weights. Every stage is the same code
# path a pod run uses — only the mesh and model are tiny.
#
# Usage: bash examples/quickstart.sh [workdir]   (default: /tmp/dolomite-quickstart)
set -euo pipefail
cd "$(dirname "$0")/.."
WORK="${1:-/tmp/dolomite-quickstart}"
export PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"
# only wipe a directory this script created (marker file), never arbitrary user data
if [ -e "$WORK" ] && [ ! -f "$WORK/.dolomite-quickstart" ]; then
  echo "refusing to delete pre-existing '$WORK' (no .dolomite-quickstart marker); pass a fresh path" >&2
  exit 1
fi
rm -rf "$WORK" && mkdir -p "$WORK" && touch "$WORK/.dolomite-quickstart"

echo "=== 1/6 tokenizer + raw corpus"
python - "$WORK" <<'EOF'
import json, random, sys
from tokenizers import Tokenizer
from tokenizers.models import WordLevel
from tokenizers.pre_tokenizers import Whitespace

work = sys.argv[1]
words = [f"w{i}" for i in range(500)]
vocab = {"<bos>": 0, "<eos>": 1, "<pad>": 2, **{w: i + 3 for i, w in enumerate(words)}}
tok = Tokenizer(WordLevel(vocab, unk_token="<eos>"))
tok.pre_tokenizer = Whitespace()
tok.save(work + "/tokenizer.json")
json.dump(
    {"tokenizer_class": "PreTrainedTokenizerFast", "bos_token": "<bos>",
     "eos_token": "<eos>", "pad_token": "<pad>"},
    open(work + "/tokenizer_config.json", "w"),
)
rng = random.Random(0)
with open(work + "/corpus.jsonl", "w") as f:
    for _ in range(400):
        f.write(json.dumps({"text": " ".join(rng.choices(words, k=rng.randint(12, 90)))}) + "\n")
print("wrote", work + "/corpus.jsonl")
EOF

echo "=== 2/6 tokenize into mmap bin/idx"
python tools/megatron_dataset/preprocess_data.py \
  --input "$WORK/corpus.jsonl" --tokenizer "$WORK" \
  --output-prefix "$WORK/corpus" --append-eod --workers 2 --chunk-size 16

echo "=== 3/6 pretrain 6 steps (ZeRO-3, packed segment ids, virtual 8-device mesh)"
python - "$WORK" <<'EOF' > "$WORK/pretrain.yml"
import sys
print(f"""
datasets:
  - class_name: MegatronDataset
    data_name: Megatron
    data_sampling_ratio: 1
    class_args:
      eval_steps: 0
      data_cache_path: {sys.argv[1]}/cache
      data_path: [{sys.argv[1]}/corpus_text]
      split: 100,0,0
      sequence_length: 64
tokenizer_args:
  tokenizer_name: {sys.argv[1]}
model_args:
  model_class: AutoModelForCausalLM
  reset_attention_mask: true
  reset_position_ids: true
  pretrained_config:
    model_type: gpt_dolomite
    vocab_size: 512
    n_positions: 64
    n_embd: 64
    n_layer: 2
    n_head: 4
    attention_head_type: mha
    position_embedding_type: rope
    activation_function: swiglu
    normalization_function: rmsnorm
    add_bias: false
    resid_pdrop: 0.0
    embd_pdrop: 0.0
    attn_pdrop: 0.0
    bos_token_id: 0
    eos_token_id: 1
    pad_token_id: 2
tuning_args: {{tuning_method: pretraining}}
distributed_args: {{stage: 3}}
training_parameters:
  num_training_steps: 6
  micro_batch_size: 2
  gradient_accumulation_steps: 1
  eval_during_training: false
save_args:
  save_path: {sys.argv[1]}/ckpt
  save_interval: 3
  async_checkpointing: true
logging_args: {{log_interval: 1}}
random_args: {{seed: 7}}
""")
EOF
python -m dolomite_engine_tpu.pretrain --config "$WORK/pretrain.yml"

echo "=== 4/6 resume for 3 more steps"
python - "$WORK" <<'EOF'
import sys
p = sys.argv[1] + "/pretrain.yml"
s = open(p).read().replace("num_training_steps: 6", "num_training_steps: 9")
s += f"\nload_args:\n  load_path: {sys.argv[1]}/ckpt\n"
open(p, "w").write(s)
EOF
python -m dolomite_engine_tpu.pretrain --config "$WORK/pretrain.yml"

echo "=== 5/6 batch generation from the checkpoint"
python - "$WORK" <<'EOF' > "$WORK/generate.yml"
import sys
print(f"""
load_args:
  load_path: {sys.argv[1]}/ckpt
datasets:
  - class_name: DebugDataset
    data_name: debug
    data_sampling_ratio: 1
    max_input_tokens: 16
    max_output_tokens: 16
    class_args: {{num_examples: 8}}
generation_parameters:
  batch_size: 4
  max_new_tokens: 8
  do_sample: false
output_dir: {sys.argv[1]}/generations
mixed_precision_args: {{dtype: fp32}}
""")
EOF
python -m dolomite_engine_tpu.generate --config "$WORK/generate.yml"
head -c 300 "$WORK"/generations/*.jsonl && echo

echo "=== 6/6 unshard to HF-layout safetensors"
python - "$WORK" <<'EOF' > "$WORK/unshard.yml"
import sys
print(f"""
load_args:
  load_path: {sys.argv[1]}/ckpt
unsharded_path: {sys.argv[1]}/hf-export
mixed_precision_args: {{dtype: fp32}}
""")
EOF
python -m dolomite_engine_tpu.unshard --config "$WORK/unshard.yml"
ls "$WORK/hf-export"

echo "=== quickstart OK: $WORK"
