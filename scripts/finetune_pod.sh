#!/bin/bash
# Multi-host finetuning launcher (parity: reference `scripts/finetune.sh`). Same contract as
# pretrain_pod.sh: run this same script on every pod host; jax.distributed.initialize()
# discovers the coordinator from the TPU metadata (or JAX_COORDINATOR_ADDRESS/
# JAX_PROCESS_COUNT/JAX_PROCESS_INDEX for manual rendezvous).
set -euo pipefail
CONFIG=${1:?"usage: finetune_pod.sh <config.yml>"}
export TOKENIZERS_PARALLELISM=false
exec python -m dolomite_engine_tpu.finetune --config "$CONFIG"
