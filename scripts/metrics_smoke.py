"""CI smoke for the live observability plane: schema <-> scrape parity.

    JAX_PLATFORMS=cpu python scripts/metrics_smoke.py

Builds a tiny randomly-initialized engine, serves a few requests with an
:class:`ObservabilityServer` attached on an ephemeral port, then scrapes the live
``/metrics`` and ``/healthz`` endpoints over HTTP and asserts:

- every ``KNOWN_COUNTERS`` name appears as a Prometheus counter (``dolomite_*_total``),
- every ``KNOWN_GAUGES`` name appears as a gauge — 0 when the run never wrote it,
- the fleet aggregation series are present (``dolomite_fleet_replicas`` etc.),
- ``/healthz`` answers 200 with a JSON body while the fleet is live.

Together with dolo-lint's ``telemetry-dead-declaration`` rule (every declared name has
an emit site) this closes the loop: what the schema tables declare, the package writes,
and a live scrape serves — none of the three can drift (docs/OBSERVABILITY.md "Live
metrics"). Exits non-zero naming the first missing metric.
"""

from __future__ import annotations

import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dolomite_engine_tpu.models.config import CommonConfig
    from dolomite_engine_tpu.models.gpt_dolomite import GPTDolomiteForCausalLM
    from dolomite_engine_tpu.serving import (
        ClusterMetricsAggregator,
        ObservabilityServer,
        ServingEngine,
        serve_batch,
    )
    from dolomite_engine_tpu.serving.obs_server import prometheus_name
    from dolomite_engine_tpu.utils.telemetry import (
        KNOWN_COUNTERS,
        KNOWN_GAUGES,
        Telemetry,
        install_telemetry,
        uninstall_telemetry,
    )

    config = CommonConfig(
        vocab_size=512,
        n_positions=128,
        n_embd=16,
        n_layer=2,
        n_head=2,
        attention_head_type="mqa",
        position_embedding_type="rope",
        resid_pdrop=0.0,
        embd_pdrop=0.0,
        attn_pdrop=0.0,
        bos_token_id=0,
        eos_token_id=1,
        pad_token_id=2,
    )
    model = GPTDolomiteForCausalLM(config=config)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    engine = ServingEngine(
        model,
        params,
        num_slots=2,
        max_len=48,
        prefill_bucket_multiple=8,
        eos_token_id=None,
        pad_token_id=config.pad_token_id,
        page_size=8,
        prefill_chunk_tokens=16,
    )

    install_telemetry(Telemetry())  # sinkless: the live registry is what we scrape
    server = ObservabilityServer(0, aggregator=ClusterMetricsAggregator([engine])).start()
    try:
        rs = np.random.RandomState(0)
        states = serve_batch(
            engine,
            [
                {
                    "prompt_ids": list(map(int, rs.randint(3, config.vocab_size, 10 + i))),
                    "max_new_tokens": 3,
                }
                for i in range(2)
            ],
        )
        assert all(s.status.value == "completed" for s in states), states

        with urllib.request.urlopen(f"{server.url}/metrics", timeout=10) as response:
            assert response.status == 200, response.status
            scrape = response.read().decode()
        lines = {line.split("{")[0].split(" ")[0] for line in scrape.splitlines()}
        missing = [
            name
            for name in sorted(KNOWN_COUNTERS)
            if prometheus_name(name, counter=True) not in lines
        ] + [name for name in sorted(KNOWN_GAUGES) if prometheus_name(name) not in lines]
        if missing:
            print(f"FAIL: /metrics is missing declared names: {missing}", file=sys.stderr)
            return 1
        for fleet_metric in ("dolomite_fleet_replicas", "dolomite_fleet_queue_depth"):
            if fleet_metric not in lines:
                print(f"FAIL: /metrics is missing fleet series {fleet_metric}", file=sys.stderr)
                return 1

        with urllib.request.urlopen(f"{server.url}/healthz", timeout=10) as response:
            assert response.status == 200, response.status
            health = json.loads(response.read().decode())
        if health.get("status") != "ok" or health.get("dead"):
            print(f"FAIL: /healthz reports unhealthy fleet: {health}", file=sys.stderr)
            return 1
    finally:
        server.stop()
        uninstall_telemetry()

    print(
        f"metrics smoke OK: {len(KNOWN_COUNTERS)} counters + {len(KNOWN_GAUGES)} gauges "
        "present in the live scrape; /healthz ok"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
