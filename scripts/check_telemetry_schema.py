"""Static telemetry-schema check — thin shim over the dolo-lint telemetry checker.

    python scripts/check_telemetry_schema.py

The implementation moved to ``tools/lint/checkers/telemetry.py`` when the check became
one rule family of the repo-wide static-analysis suite (``python -m tools.lint``); this
entrypoint and its ``check_package()`` API are kept stable for existing callers and
tests. Semantics are unchanged: every literal telemetry call site under
``dolomite_engine_tpu/`` must use a name declared in ``utils/telemetry.py``'s tables,
record literals must carry their kind's required fields, and every declared name must
have a call site (no schema rot). See docs/STATIC_ANALYSIS.md for the rule catalog.
"""

from __future__ import annotations

import ast
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE_DIR = os.path.join(REPO_ROOT, "dolomite_engine_tpu")
sys.path.insert(0, REPO_ROOT)


def check_package(package_dir: str = PACKAGE_DIR) -> list[str]:
    """Walk `package_dir` and return error strings (empty = clean). Same output format
    as the original standalone checker."""
    from tools.lint.checkers.telemetry import Usage, load_tables, reverse_errors, scan_tree

    tables = load_tables()
    errors: list[str] = []
    usage = Usage()
    for dirpath, _dirnames, filenames in os.walk(package_dir):
        for filename in sorted(filenames):
            if not filename.endswith(".py"):
                continue
            path = os.path.join(dirpath, filename)
            rel = os.path.relpath(path, REPO_ROOT)
            with open(path) as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError as error:
                    errors.append(f"{rel}: unparseable: {error}")
                    continue
            file_errors, file_usage = scan_tree(tree, path, tables)
            errors.extend(f"{rel}:{line}: {msg}" for line, msg in file_errors)
            usage.update(file_usage)
    errors.extend(reverse_errors(tables, usage))
    return errors


def main() -> int:
    errors = check_package()
    if errors:
        print(f"telemetry schema check FAILED ({len(errors)} error(s)):", file=sys.stderr)
        for error in errors:
            print(f"  {error}", file=sys.stderr)
        return 1
    print("telemetry schema check OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
