"""Static telemetry-schema check: every sink call site must use a declared name.

    python scripts/check_telemetry_schema.py

Walks every ``.py`` file under ``dolomite_engine_tpu/`` with ``ast`` (no execution of the
scanned code) and validates each telemetry call site against the tables declared in
``dolomite_engine_tpu/utils/telemetry.py``:

- ``*.count("name", ...)``       -> name in ``KNOWN_COUNTERS``; with ``event=True`` the name
  must also be in ``KNOWN_EVENTS`` (it writes an event record under that name)
- ``*.event("name", ...)``       -> name in ``KNOWN_EVENTS``
- ``*.gauge("name", ...)``       -> name in ``KNOWN_GAUGES`` (dynamic names — the
  per-device memory fan-out — are exempt, same rule as counters)
- ``*.emit_record("kind", ...)`` -> kind in ``RECORD_SCHEMA``; literal keyword fields must
  cover the kind's required fields (calls forwarding ``**fields`` are kind-checked only)
- ``{"kind": "x", ...}`` dict literals (the internal ``_emit`` payloads) -> kind declared in
  ``RECORD_SCHEMA`` and literal keys covering its required fields

Only calls whose receiver mentions ``telemetry`` (``telemetry.count``,
``get_telemetry().count``, ``self.telemetry.event``) or ``self`` within the telemetry module
itself are considered, so unrelated ``.count()``/``.get()`` methods don't false-positive.
Dynamic (non-literal) names are skipped — the tables bound what *can* be written literally,
which is every production call site today. Unused declared names are reported as errors too,
so the table can't accrete dead entries.

Run as a tier-1 test (tests/test_diagnostics.py) so a new record type or counter cannot
ship without being declared here and documented in docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import ast
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE_DIR = os.path.join(REPO_ROOT, "dolomite_engine_tpu")
sys.path.insert(0, REPO_ROOT)

# the modules allowed to call the registry through `self` / `self.telemetry`
_SELF_CALL_FILES = ("telemetry.py", "diagnostics.py")


def _is_telemetry_receiver(call: ast.Call, filename: str) -> bool:
    receiver = call.func.value  # type: ignore[union-attr]
    try:
        text = ast.unparse(receiver)
    except Exception:
        return False
    if "telemetry" in text.lower():
        return True
    return text == "self" and os.path.basename(filename) in _SELF_CALL_FILES


def _literal_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def check_package(package_dir: str = PACKAGE_DIR) -> list[str]:
    from dolomite_engine_tpu.utils.telemetry import (
        KNOWN_COUNTERS,
        KNOWN_EVENTS,
        KNOWN_GAUGES,
        RECORD_SCHEMA,
    )

    errors: list[str] = []
    used_counters: set[str] = set()
    used_events: set[str] = set()
    used_gauges: set[str] = set()
    used_kinds: set[str] = set()

    for dirpath, _dirnames, filenames in os.walk(package_dir):
        for filename in sorted(filenames):
            if not filename.endswith(".py"):
                continue
            path = os.path.join(dirpath, filename)
            rel = os.path.relpath(path, REPO_ROOT)
            with open(path) as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError as error:
                    errors.append(f"{rel}: unparseable: {error}")
                    continue

            for node in ast.walk(tree):
                # {"kind": "x", ...} literals — the internal _emit payloads
                if isinstance(node, ast.Dict):
                    keys = [_literal_str(k) for k in node.keys if k is not None]
                    if "kind" not in keys:
                        continue
                    kind = _literal_str(node.values[keys.index("kind")])
                    if kind is None:
                        continue
                    used_kinds.add(kind)
                    if kind not in RECORD_SCHEMA:
                        errors.append(
                            f"{rel}:{node.lineno}: record kind '{kind}' not declared in "
                            "RECORD_SCHEMA"
                        )
                        continue
                    literal_keys = {k for k in keys if k}
                    missing = [
                        f for f in RECORD_SCHEMA[kind] if f not in literal_keys
                    ]
                    # payloads assembled incrementally (record.update / **fields) only
                    # carry some keys literally; require the declared fields only when the
                    # literal looks complete (no dynamic construction around it is
                    # detectable, so use: more literal keys than just "kind")
                    if missing and len(literal_keys) > 1:
                        errors.append(
                            f"{rel}:{node.lineno}: record kind '{kind}' literal is missing "
                            f"required field(s) {missing}"
                        )
                    continue

                if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
                    continue
                method = node.func.attr
                if method not in ("count", "event", "gauge", "emit_record"):
                    continue
                if not _is_telemetry_receiver(node, path):
                    continue
                name = _literal_str(node.args[0]) if node.args else None
                if name is None:
                    continue  # dynamic name (e.g. count()'s internal event fan-out)

                if method == "count":
                    used_counters.add(name)
                    if name not in KNOWN_COUNTERS:
                        errors.append(
                            f"{rel}:{node.lineno}: counter '{name}' not in KNOWN_COUNTERS"
                        )
                    wants_event = any(
                        kw.arg == "event"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True
                        for kw in node.keywords
                    )
                    if wants_event:
                        used_events.add(name)
                        if name not in KNOWN_EVENTS:
                            errors.append(
                                f"{rel}:{node.lineno}: counter '{name}' emits an event "
                                "(event=True) but is not in KNOWN_EVENTS"
                            )
                elif method == "event":
                    used_events.add(name)
                    if name not in KNOWN_EVENTS:
                        errors.append(
                            f"{rel}:{node.lineno}: event '{name}' not in KNOWN_EVENTS"
                        )
                elif method == "gauge":
                    used_gauges.add(name)
                    if name not in KNOWN_GAUGES:
                        errors.append(
                            f"{rel}:{node.lineno}: gauge '{name}' not in KNOWN_GAUGES"
                        )
                elif method == "emit_record":
                    used_kinds.add(name)
                    if name not in RECORD_SCHEMA:
                        errors.append(
                            f"{rel}:{node.lineno}: record kind '{name}' not declared in "
                            "RECORD_SCHEMA"
                        )
                    elif not any(isinstance(a, ast.keyword) and a.arg is None for a in node.keywords):
                        # no **fields forwarding: the literal keywords must cover the schema
                        literal_kw = {kw.arg for kw in node.keywords if kw.arg} | {"step"}
                        missing = [
                            f for f in RECORD_SCHEMA[name] if f not in literal_kw
                        ]
                        if missing:
                            errors.append(
                                f"{rel}:{node.lineno}: emit_record('{name}') is missing "
                                f"required field(s) {missing}"
                            )

    # reverse direction: a declared name nobody writes is dead weight / schema rot
    for name in KNOWN_COUNTERS:
        if name not in used_counters:
            errors.append(f"KNOWN_COUNTERS entry '{name}' has no call site in the package")
    for name in KNOWN_EVENTS:
        if name not in used_events:
            errors.append(f"KNOWN_EVENTS entry '{name}' has no call site in the package")
    for name in KNOWN_GAUGES:
        if name not in used_gauges:
            errors.append(f"KNOWN_GAUGES entry '{name}' has no call site in the package")
    for kind in RECORD_SCHEMA:
        if kind not in used_kinds:
            errors.append(f"RECORD_SCHEMA kind '{kind}' is never written in the package")

    return errors


def main() -> int:
    errors = check_package()
    if errors:
        print(f"telemetry schema check FAILED ({len(errors)} error(s)):", file=sys.stderr)
        for error in errors:
            print(f"  {error}", file=sys.stderr)
        return 1
    print("telemetry schema check OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
