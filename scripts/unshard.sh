#!/bin/bash
# Parity: reference `scripts/unshard.sh`.
python -m dolomite_engine_tpu.unshard --config ${1}
