#!/bin/bash
# Multi-host TPU pod launcher (parity: reference `scripts/pretrain.sh`, which derives
# node rank / master address from the LSF env and torchruns dolomite_engine.pretrain).
#
# On a TPU pod slice (e.g. v5e-256 = 64 hosts x 4 chips), run THIS SAME SCRIPT on every
# host (gcloud alpha compute tpus tpu-vm ssh $TPU_NAME --worker=all --command="..."):
# jax.distributed.initialize() discovers the coordinator and the host's process index from
# the TPU metadata server automatically — no torchrun/rendezvous flags needed.
#
#   ./scripts/pretrain_pod.sh configs/pretrain.yml
#
# Off-GCP / manual rendezvous (e.g. bare-metal pods, CPU smoke tests): set
#   JAX_COORDINATOR_ADDRESS=<host0-ip>:<port>   # same on every host
#   JAX_PROCESS_COUNT=<num_hosts>               # total host count
#   JAX_PROCESS_INDEX=<this-host-rank>          # 0..num_hosts-1
# dolomite_engine_tpu.utils.init_distributed() forwards them to
# jax.distributed.initialize() (utils/__init__.py:33-58).
#
# Data: each host reads only its 1/num_hosts share of the global batch
# (data/megatron/__init__.py MegatronBatchSampler(num_replicas=num_hosts, rank=host_rank));
# ShardedDataLoader assembles the global array with
# jax.make_array_from_process_local_data — no cross-host data traffic. Host 0 builds the
# megatron index caches first; other hosts wait on a barrier, then mmap the same caches
# (requires data_cache_path on a shared filesystem, same as the reference's Megatron
# pipeline).
#
# Checkpoints: orbax writes per-host shards of the sharded TrainState; rng/dataloader
# state is saved per process (checkpointing.py) — resume with the same host count.

set -euo pipefail

CONFIG=${1:?"usage: pretrain_pod.sh <config.yml>"}

export TOKENIZERS_PARALLELISM=false
# one python process per host drives all local chips; jax.distributed.initialize() is
# called inside (guarded by the env heuristics in utils.init_distributed)
exec python -m dolomite_engine_tpu.pretrain --config "$CONFIG"
