#!/bin/bash
# Parity: reference `scripts/generate.sh`.
python -m dolomite_engine_tpu.generate --config ${1}
